"""Llama-family decoder-only LM (the framework's flagship model).

Covers Llama-2/3 shapes: RMSNorm, RoPE, grouped-query attention, SwiGLU
MLP, optional tied embeddings. Pure-functional, stacked-layer params
scanned with ``lax.scan`` (see gofr_tpu.models.base docstring).

Three jittable entry points:
- ``forward``          full causal pass, no cache (training / scoring)
- ``prefill``          writes prompt K/V into SlotKVCache slots, returns
                       last-position logits
- ``decode_step``      one token per active slot, appends K/V in place

TP sharding is expressed through logical axes (``param_axes``): heads /
kv_heads / mlp / vocab shard over "tp", giving the standard Megatron-style
column→row parallel layout per block — XLA inserts the psum on wo/w_down
(reference capability map: SURVEY.md §2.9 — this subsystem is new, the
reference has no model layer).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from gofr_tpu.models.base import fan_in_init, truncated_normal
from gofr_tpu.ops import apply_rope, mha_attention, rms_norm, rope_table
from gofr_tpu.ops.attention import decode_attention, decode_attention_q, paged_decode_attention
from gofr_tpu.ops.quant import qdot
from gofr_tpu.ops.kvcache import (
    QSlotKVCache,
    SlotKVCache,
    append_tokens,
    append_tokens_q,
    dequantize_view,
    fake_quant_row,
    write_prompts,
    write_prompts_q,
)
from gofr_tpu.ops.attention import paged_decode_attention_q, paged_decode_attention_q4
from gofr_tpu.ops.paged import (
    PagedKVCache,
    Q4PagedKVCache,
    QPagedKVCache,
    append_tokens_paged,
    append_tokens_paged_q,
    append_tokens_paged_q4,
    gather_kv,
    gather_kv_q,
    gather_kv_q4,
    write_prompts_paged,
    write_prompts_paged_q,
    write_prompts_paged_q4,
)
from gofr_tpu.ops.quant import fake_quant_row_int4
from gofr_tpu.ops.lora import lora_logits_delta

# Serving entry points accept a per-lane LoRA pool (``adapters`` kwarg:
# (sel, a, b, scale); ops/lora.py) — build_programs keys on this flag.
SUPPORTS_ADAPTERS = True


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int | None = None
    rope_theta: float = 500000.0
    max_seq_len: int = 8192
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16

    @property
    def head_size(self) -> int:
        return self.head_dim if self.head_dim is not None else self.hidden_size // self.num_heads

    # -- presets ---------------------------------------------------------------

    @classmethod
    def llama3_8b(cls, **kw) -> "LlamaConfig":
        return cls(**{**dict(
            vocab_size=128256, hidden_size=4096, intermediate_size=14336,
            num_layers=32, num_heads=32, num_kv_heads=8, rope_theta=500000.0,
        ), **kw})

    @classmethod
    def llama3_70b(cls, **kw) -> "LlamaConfig":
        return cls(**{**dict(
            vocab_size=128256, hidden_size=8192, intermediate_size=28672,
            num_layers=80, num_heads=64, num_kv_heads=8, rope_theta=500000.0,
        ), **kw})

    @classmethod
    def one_b(cls, **kw) -> "LlamaConfig":
        """~1B-param config that fits one v5e chip in bf16 with headroom."""
        return cls(**{**dict(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_layers=22, num_heads=32, num_kv_heads=4, rope_theta=10000.0,
        ), **kw})

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        """Test-sized config for the CPU mesh."""
        return cls(**{**dict(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
            rope_theta=10000.0, dtype=jnp.float32,
        ), **kw})


# -- params --------------------------------------------------------------------


def init(cfg: LlamaConfig, key: jax.Array) -> dict:
    e, m, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    hq, hkv, d, nl = cfg.num_heads, cfg.num_kv_heads, cfg.head_size, cfg.num_layers
    keys = jax.random.split(key, 9)
    dt = cfg.dtype

    params = {
        "embed": truncated_normal(keys[0], (v, e), 0.02, dt),
        "blocks": {
            "attn_norm": jnp.ones((nl, e), dt),
            "wq": fan_in_init(keys[1], (nl, e, hq * d), fan_in=e, dtype=dt),
            "wk": fan_in_init(keys[2], (nl, e, hkv * d), fan_in=e, dtype=dt),
            "wv": fan_in_init(keys[3], (nl, e, hkv * d), fan_in=e, dtype=dt),
            "wo": fan_in_init(keys[4], (nl, hq * d, e), fan_in=hq * d, dtype=dt),
            "mlp_norm": jnp.ones((nl, e), dt),
            "w_gate": fan_in_init(keys[5], (nl, e, m), fan_in=e, dtype=dt),
            "w_up": fan_in_init(keys[6], (nl, e, m), fan_in=e, dtype=dt),
            "w_down": fan_in_init(keys[7], (nl, m, e), fan_in=m, dtype=dt),
        },
        "final_norm": jnp.ones((e,), dt),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = truncated_normal(keys[8], (e, v), 0.02, dt)
    return params


# every linear site routes through ops.quant.qdot, so QTensor params serve
QUANTIZABLE = True
# prefill() accepts chunk offsets, so the slot-layout engine can stream
# long prompts in chunks too (the paged layout has prefill_paged for this)
SLOT_CHUNKED_PREFILL = True


def param_axes(cfg: LlamaConfig) -> dict:
    """Logical sharding axes matching ``init``'s pytree (see
    gofr_tpu.parallel.sharding)."""
    axes = {
        "embed": ("vocab", "embed"),
        "blocks": {
            "attn_norm": ("layers", None),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "mlp_norm": ("layers", None),
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        },
        "final_norm": (None,),
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def _rope(cfg: LlamaConfig):
    return rope_table(cfg.max_seq_len, cfg.head_size, theta=cfg.rope_theta)


# -- block ---------------------------------------------------------------------


def _qkv(cfg: LlamaConfig, lp: dict, x: jnp.ndarray):
    """x [B,S,E] → q [B,S,Hq,D], k/v [B,S,Hkv,D] (post-norm, pre-rope)."""
    b, s, _ = x.shape
    h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = qdot(h, lp["wq"]).reshape(b, s, cfg.num_heads, cfg.head_size)
    k = qdot(h, lp["wk"]).reshape(b, s, cfg.num_kv_heads, cfg.head_size)
    v = qdot(h, lp["wv"]).reshape(b, s, cfg.num_kv_heads, cfg.head_size)
    return q, k, v


def _mlp(cfg: LlamaConfig, lp: dict, x: jnp.ndarray) -> jnp.ndarray:
    h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    gated = jax.nn.silu(qdot(h, lp["w_gate"])) * qdot(h, lp["w_up"])
    return qdot(gated, lp["w_down"])


# -- entry points --------------------------------------------------------------


@partial(jax.jit, static_argnums=(0, 4, 5))
def forward(cfg: LlamaConfig, params: dict, tokens: jnp.ndarray,
            lengths: jnp.ndarray | None = None,
            attn_fn: Any = None, head_fn: Any = None) -> jnp.ndarray:
    """Full causal forward, no cache: tokens [B,S] → logits [B,S,V] (f32).
    ``lengths`` masks padded positions out of attention.

    ``attn_fn`` swaps the attention implementation (static; same contract
    as ops.mha_attention) — e.g. a mesh-bound ring/Ulysses sequence-parallel
    attention from gofr_tpu.parallel.ring.make_seq_parallel_attn.

    ``head_fn`` swaps the lm_head projection (static; ``(x, head) ->
    logits``) — e.g. the quality plane's LoRA-delta head, which must score
    teacher-forced sequences with the exact adapter math serving used."""
    attn = attn_fn or mha_attention
    cos, sin = _rope(cfg)
    x = params["embed"][tokens].astype(cfg.dtype)
    b, s = tokens.shape
    positions = jnp.arange(s)[None]

    def body(x, lp):
        q, k, v = _qkv(cfg, lp, x)
        q = apply_rope(q, positions, cos, sin)
        k = apply_rope(k, positions, cos, sin)
        a = attn(q, k, v, causal=True, kv_lengths=lengths)
        x = x + qdot(a.reshape(b, s, -1), lp["wo"])
        x = x + _mlp(cfg, lp, x)
        return x, None

    x, _ = lax.scan(body, x, params["blocks"])
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = head_fn(x, head) if head_fn is not None else qdot(x, head)
    return logits.astype(jnp.float32)


@partial(jax.jit, static_argnums=(0, 4, 5))
def forward_pipelined(cfg: LlamaConfig, params: dict, tokens: jnp.ndarray,
                      lengths: jnp.ndarray, mesh: Any,
                      microbatches: int = 4) -> jnp.ndarray:
    """Pipeline-parallel full forward: blocks shard over the mesh's ``pp``
    axis (leading layers dim) and microbatches stream through the stage
    ring (gofr_tpu.parallel.pipeline). Embed/norm/head stay replicated.
    Requires num_layers % pp == 0 and batch % microbatches == 0.

    Composes with tp: heads/mlp dims of the stage weights stay tp-sharded
    inside the pipeline region (manual Megatron-style psums after wo and
    w_down), so pp×tp meshes neither replicate weights nor duplicate
    compute."""
    from gofr_tpu.parallel.pipeline import make_pipeline_forward
    from gofr_tpu.parallel.sharding import ShardingRules

    cos, sin = _rope(cfg)
    x = params["embed"][tokens].astype(cfg.dtype)
    s = tokens.shape[1]
    positions = jnp.arange(s)[None]
    d = cfg.head_size
    tp = "tp" if "tp" in mesh.axis_names and mesh.shape["tp"] > 1 else None

    def stage(blocks_local, x, lens):
        b = x.shape[0]

        def body(x, lp):
            # local-head qkv: head counts come from the tp-sharded weights
            h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
            q = (h @ lp["wq"]).reshape(b, s, -1, d)
            k = (h @ lp["wk"]).reshape(b, s, -1, d)
            v = (h @ lp["wv"]).reshape(b, s, -1, d)
            q = apply_rope(q, positions, cos, sin)
            k = apply_rope(k, positions, cos, sin)
            a = mha_attention(q, k, v, causal=True, kv_lengths=lens)
            o = a.reshape(b, s, -1) @ lp["wo"]
            if tp:
                o = lax.psum(o, tp)
            x = x + o
            h2 = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
            mo = (jax.nn.silu(h2 @ lp["w_gate"]) * (h2 @ lp["w_up"])) @ lp["w_down"]
            if tp:
                mo = lax.psum(mo, tp)
            return x + mo, None

        x, _ = lax.scan(body, x, blocks_local)
        return x

    rules = ShardingRules().with_overrides(layers="pp")
    block_specs = {
        name: rules.spec(axes, mesh)
        for name, axes in param_axes(cfg)["blocks"].items()
    }
    pp_forward = make_pipeline_forward(
        mesh, microbatches=microbatches, param_specs=block_specs
    )
    x = pp_forward(stage, params["blocks"], x, lengths)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    return qdot(x, head).astype(jnp.float32)


@partial(jax.jit, static_argnums=0, static_argnames=("attn_fn",), donate_argnums=4)
def prefill(cfg: LlamaConfig, params: dict, tokens: jnp.ndarray, lengths: jnp.ndarray,
            cache: SlotKVCache, slots: jnp.ndarray,
            offsets: jnp.ndarray | None = None, *,
            attn_fn: Any = None,
            adapters=None) -> tuple[jnp.ndarray, SlotKVCache]:
    """Prefill prompts (or prompt CHUNKS) into cache slots.

    tokens [B,S] (padded), lengths [B] = live tokens in this call, slots
    [B] → (last-token logits [B,V] f32, updated cache). ``offsets`` [B]
    places the chunk at logical positions offsets..offsets+S (None = 0,
    whole-prompt prefill). Chunked rows attend to everything already in
    their slot through a gathered cache view; whole-prompt rows attend
    prompt-locally.

    ``attn_fn`` swaps the whole-prompt attention (same contract as
    ops.mha_attention) — e.g. a mesh-bound ring/Ulysses sequence-parallel
    attention (parallel.ring.make_seq_parallel_attn) so long-prompt
    prefill shards the sequence over an ``sp`` axis. Whole-prompt rows
    only: the chunked path's gathered-view attention stays as is.
    """
    if attn_fn is not None and offsets is not None:
        raise ValueError("attn_fn applies to whole-prompt prefill only (offsets=None)")
    cos, sin = _rope(cfg)
    x = params["embed"][tokens].astype(cfg.dtype)
    b, s = tokens.shape
    chunked = offsets is not None
    positions = (offsets[:, None] if chunked else 0) + jnp.arange(s)[None]
    row = jnp.arange(b)
    total = (offsets + lengths) if chunked else lengths
    quant = isinstance(cache, QSlotKVCache)  # int8 KV storage (kvcache.py)

    def body(x, xs):
        if quant:
            lp, k_layer, ks_l, v_layer, vs_l = xs
        else:
            lp, k_layer, v_layer = xs
        q, k, v = _qkv(cfg, lp, x)
        q = apply_rope(q, positions, cos, sin)
        k = apply_rope(k, positions, cos, sin)
        if quant:
            k_layer, ks_l = write_prompts_q(k_layer, ks_l, slots, k, offsets)
            v_layer, vs_l = write_prompts_q(v_layer, vs_l, slots, v, offsets)
        else:
            k_layer, v_layer = write_prompts(k_layer, v_layer, slots, k, v, offsets)
        if chunked:
            if quant:
                k_view = dequantize_view(jnp.take(k_layer, slots, axis=0),
                                         jnp.take(ks_l, slots, axis=0), cfg.dtype)
                v_view = dequantize_view(jnp.take(v_layer, slots, axis=0),
                                         jnp.take(vs_l, slots, axis=0), cfg.dtype)
            else:
                k_view = jnp.take(k_layer, slots, axis=0)  # [B, Hkv, Smax, D]
                v_view = jnp.take(v_layer, slots, axis=0)
            attn = mha_attention(
                q, k_view.swapaxes(1, 2), v_view.swapaxes(1, 2),
                causal=True, q_offset=offsets, kv_lengths=total,
            )
        elif quant:
            # self-consistency with the int8 cache (see prefill_paged)
            attn = (attn_fn or mha_attention)(
                q, fake_quant_row(k), fake_quant_row(v),
                causal=True, kv_lengths=lengths)
        else:
            attn = (attn_fn or mha_attention)(q, k, v, causal=True, kv_lengths=lengths)
        x = x + qdot(attn.reshape(b, s, -1), lp["wo"])
        x = x + _mlp(cfg, lp, x)
        return x, (k_layer, ks_l, v_layer, vs_l) if quant else (k_layer, v_layer)

    if quant:
        xs = (params["blocks"], cache.k, cache.ks, cache.v, cache.vs)
        x, (new_k, new_ks, new_v, new_vs) = lax.scan(body, x, xs)
        out_cache = QSlotKVCache(k=new_k, v=new_v, ks=new_ks, vs=new_vs)
    else:
        x, (new_k, new_v) = lax.scan(body, x, (params["blocks"], cache.k, cache.v))
        out_cache = SlotKVCache(k=new_k, v=new_v)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    last = x[row, lengths - 1]  # [B,E]
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = qdot(last, head).astype(jnp.float32)
    if adapters is not None:
        logits = logits + lora_logits_delta(last, adapters)
    return logits, out_cache


@partial(jax.jit, static_argnums=0, donate_argnums=4)
def verify_step(cfg: LlamaConfig, params: dict, tokens: jnp.ndarray,
                positions: jnp.ndarray, cache: SlotKVCache,
                adapters=None) -> tuple[jnp.ndarray, SlotKVCache]:
    """Speculative-decoding verification (engine.spec_tokens): one forward
    over ``tokens`` [N, T] per slot — the current input token plus T-1
    draft tokens — written and attended at positions ``positions[n]`` ..
    ``positions[n]+T-1`` of slot n's cache. Returns logits [N, T, V] (f32,
    the target's next-token distribution AFTER each of the T tokens) and
    the updated cache.

    Draft K/V beyond the accepted prefix go stale in the cache but are
    always overwritten before they can be attended: the next round's write
    range starts at the new input position and covers every stale slot
    before its per-layer attention runs (engine._spec_chunk invariants).
    Out-of-bounds positions (inactive lanes) drop their writes — the same
    convention as prefill padding rows."""
    cos, sin = _rope(cfg)
    x = params["embed"][tokens].astype(cfg.dtype)
    n, t = tokens.shape
    pos2d = positions[:, None] + jnp.arange(t)[None]
    total = positions + t
    rows = jnp.arange(n)
    quant = isinstance(cache, QSlotKVCache)

    def body(x, xs):
        if quant:
            lp, k_layer, ks_l, v_layer, vs_l = xs
        else:
            lp, k_layer, v_layer = xs
        q, k, v = _qkv(cfg, lp, x)
        q = apply_rope(q, pos2d, cos, sin)
        k = apply_rope(k, pos2d, cos, sin)
        if quant:
            k_layer, ks_l = write_prompts_q(k_layer, ks_l, rows, k, positions)
            v_layer, vs_l = write_prompts_q(v_layer, vs_l, rows, v, positions)
            k_view = dequantize_view(k_layer, ks_l, cfg.dtype)
            v_view = dequantize_view(v_layer, vs_l, cfg.dtype)
        else:
            k_layer, v_layer = write_prompts(k_layer, v_layer, rows, k, v, positions)
            k_view, v_view = k_layer, v_layer
        attn = mha_attention(
            q, k_view.swapaxes(1, 2), v_view.swapaxes(1, 2),
            causal=True, q_offset=positions, kv_lengths=total,
        )
        x = x + qdot(attn.reshape(n, t, -1), lp["wo"])
        x = x + _mlp(cfg, lp, x)
        return x, (k_layer, ks_l, v_layer, vs_l) if quant else (k_layer, v_layer)

    if quant:
        xs = (params["blocks"], cache.k, cache.ks, cache.v, cache.vs)
        x, (new_k, new_ks, new_v, new_vs) = lax.scan(body, x, xs)
        out_cache = QSlotKVCache(k=new_k, v=new_v, ks=new_ks, vs=new_vs)
    else:
        x, (new_k, new_v) = lax.scan(body, x, (params["blocks"], cache.k, cache.v))
        out_cache = SlotKVCache(k=new_k, v=new_v)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = qdot(x, head).astype(jnp.float32)
    if adapters is not None:
        logits = logits + lora_logits_delta(x, adapters)
    return logits, out_cache


@partial(jax.jit, static_argnums=0, donate_argnums=4)
def decode_step(cfg: LlamaConfig, params: dict, tokens: jnp.ndarray, positions: jnp.ndarray,
                cache: SlotKVCache,
                adapters=None) -> tuple[jnp.ndarray, SlotKVCache]:
    """One decode step over every slot.

    tokens [N] (next input token per slot), positions [N] (where it goes in
    the cache = current sequence length), over the full slot batch
    N == cache.num_slots. Returns (logits [N,V] f32, updated cache).
    Inactive slots simply produce garbage logits the engine ignores —
    uniform work keeps the step a single fixed XLA program.
    """
    cos, sin = _rope(cfg)
    x = params["embed"][tokens].astype(cfg.dtype)  # [N,E]
    n = tokens.shape[0]
    pos1 = positions[:, None]  # [N,1]
    quant = isinstance(cache, QSlotKVCache)

    def body(x, xs):
        if quant:
            lp, k_layer, ks_l, v_layer, vs_l = xs
        else:
            lp, k_layer, v_layer = xs
        q, k, v = _qkv(cfg, lp, x[:, None])  # seq dim of 1
        q = apply_rope(q, pos1, cos, sin)[:, 0]  # [N,Hq,D]
        k = apply_rope(k, pos1, cos, sin)[:, 0]
        v = v[:, 0]
        if quant:
            k_layer, ks_l = append_tokens_q(k_layer, ks_l, positions, k)
            v_layer, vs_l = append_tokens_q(v_layer, vs_l, positions, v)
            attn = decode_attention_q(q, k_layer, v_layer, ks_l, vs_l, positions + 1)
        else:
            k_layer, v_layer = append_tokens(k_layer, v_layer, positions, k, v)
            attn = decode_attention(q, k_layer, v_layer, positions + 1)
        x = x + qdot(attn.reshape(n, -1), lp["wo"])
        x = x + _mlp(cfg, lp, x)
        return x, (k_layer, ks_l, v_layer, vs_l) if quant else (k_layer, v_layer)

    if quant:
        xs = (params["blocks"], cache.k, cache.ks, cache.v, cache.vs)
        x, (new_k, new_ks, new_v, new_vs) = lax.scan(body, x, xs)
        out_cache = QSlotKVCache(k=new_k, v=new_v, ks=new_ks, vs=new_vs)
    else:
        x, (new_k, new_v) = lax.scan(body, x, (params["blocks"], cache.k, cache.v))
        out_cache = SlotKVCache(k=new_k, v=new_v)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = qdot(x, head).astype(jnp.float32)
    if adapters is not None:
        logits = logits + lora_logits_delta(x, adapters)
    return logits, out_cache


def make_cache(cfg: LlamaConfig, slots: int, max_len: int | None = None) -> SlotKVCache:
    return SlotKVCache.create(
        cfg.num_layers, slots, max_len or cfg.max_seq_len, cfg.num_kv_heads,
        cfg.head_size, dtype=cfg.dtype,
    )


def make_cache_q(cfg: LlamaConfig, slots: int, max_len: int | None = None) -> QSlotKVCache:
    """int8 KV cache (kvcache.QSlotKVCache): same serving contract as
    make_cache — prefill/decode_step/verify_step branch on the cache type."""
    return QSlotKVCache.create(
        cfg.num_layers, slots, max_len or cfg.max_seq_len, cfg.num_kv_heads,
        cfg.head_size,
    )


# -- paged-cache entry points (ops.paged; SURVEY.md §7 stage 4) -----------------


@partial(jax.jit, static_argnums=0, donate_argnums=4)
def verify_step_paged(cfg: LlamaConfig, params: dict, tokens: jnp.ndarray,
                      positions: jnp.ndarray, cache, table: jnp.ndarray,
                      adapters=None):
    """Speculative-decoding verification against the paged pool — the
    contract and stale-draft-KV invariants of ``verify_step``, with writes
    routed through per-slot block tables (``table`` [N, MaxP]; OOB rows
    drop) and attention over the gathered logical views. Handles the
    dense, int8, and packed-int4 pools (cache-type branch, like
    decode_step_paged — the quantized layouts share plane names, so only
    the write/gather helpers differ)."""
    cos, sin = _rope(cfg)
    x = params["embed"][tokens].astype(cfg.dtype)
    n, t = tokens.shape
    pos2d = positions[:, None] + jnp.arange(t)[None]
    total = positions + t
    q4c = isinstance(cache, Q4PagedKVCache)
    quant = q4c or isinstance(cache, QPagedKVCache)
    wpp = write_prompts_paged_q4 if q4c else write_prompts_paged_q
    gkv = gather_kv_q4 if q4c else gather_kv_q
    out_cls = Q4PagedKVCache if q4c else QPagedKVCache

    def body(x, xs):
        if quant:
            lp, k_layer, ks_l, v_layer, vs_l = xs
        else:
            lp, k_layer, v_layer = xs
        q, k, v = _qkv(cfg, lp, x)
        q = apply_rope(q, pos2d, cos, sin)
        k = apply_rope(k, pos2d, cos, sin)
        if quant:
            k_layer, ks_l = wpp(k_layer, ks_l, table, k, positions)
            v_layer, vs_l = wpp(v_layer, vs_l, table, v, positions)
            gkq, gks = gkv(k_layer, ks_l, table)
            gvq, gvs = gkv(v_layer, vs_l, table)
            k_view = dequantize_view(gkq, gks, cfg.dtype)
            v_view = dequantize_view(gvq, gvs, cfg.dtype)
        else:
            k_layer, v_layer = write_prompts_paged(k_layer, v_layer, table, k, v, positions)
            k_view, v_view = gather_kv(k_layer, v_layer, table)
        attn = mha_attention(
            q, k_view.swapaxes(1, 2), v_view.swapaxes(1, 2),
            causal=True, q_offset=positions, kv_lengths=total,
        )
        x = x + qdot(attn.reshape(n, t, -1), lp["wo"])
        x = x + _mlp(cfg, lp, x)
        return x, (k_layer, ks_l, v_layer, vs_l) if quant else (k_layer, v_layer)

    if quant:
        xs = (params["blocks"], cache.k, cache.ks, cache.v, cache.vs)
        x, (new_k, new_ks, new_v, new_vs) = lax.scan(body, x, xs)
        out_cache = out_cls(k=new_k, v=new_v, ks=new_ks, vs=new_vs)
    else:
        x, (new_k, new_v) = lax.scan(body, x, (params["blocks"], cache.k, cache.v))
        out_cache = PagedKVCache(k=new_k, v=new_v)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = qdot(x, head).astype(jnp.float32)
    if adapters is not None:
        logits = logits + lora_logits_delta(x, adapters)
    return logits, out_cache


def make_paged_cache(cfg: LlamaConfig, pages: int, page_size: int = 128,
                     sharding=None) -> PagedKVCache:
    return PagedKVCache.create(
        cfg.num_layers, pages, page_size, cfg.num_kv_heads, cfg.head_size,
        dtype=cfg.dtype, sharding=sharding,
    )


def make_paged_cache_q(cfg: LlamaConfig, pages: int, page_size: int = 128,
                       sharding=None) -> QPagedKVCache:
    """int8 paged pool (ops.paged.QPagedKVCache): prefill_paged /
    decode_step_paged branch on the cache type, like the slot layout."""
    return QPagedKVCache.create(
        cfg.num_layers, pages, page_size, cfg.num_kv_heads, cfg.head_size,
        sharding=sharding,
    )


def make_paged_cache_q4(cfg: LlamaConfig, pages: int, page_size: int = 128,
                        sharding=None) -> Q4PagedKVCache:
    """Packed-int4 paged pool (ops.paged.Q4PagedKVCache): same plane names
    as the int8 pool so the scan xs plumbing is shared; only the per-plane
    write/gather/attention helpers differ (cache-type branch)."""
    return Q4PagedKVCache.create(
        cfg.num_layers, pages, page_size, cfg.num_kv_heads, cfg.head_size,
        sharding=sharding,
    )


@partial(jax.jit, static_argnums=0, static_argnames=("attn_fn",), donate_argnums=4)
def prefill_paged(
    cfg: LlamaConfig, params: dict, tokens: jnp.ndarray, lengths: jnp.ndarray,
    cache: PagedKVCache, pages: jnp.ndarray, offsets: jnp.ndarray | None = None,
    *, attn_fn: Any = None, adapters=None,
) -> tuple[jnp.ndarray, PagedKVCache]:
    """Prefill prompts (or prompt CHUNKS) through per-row block tables.

    tokens [B,S] (padded), lengths [B] = live tokens in THIS chunk,
    ``pages`` [B, MaxP] = the full block table row per request (OOB = pool
    size for padding rows / unallocated pages). ``offsets`` [B] places the
    chunk at logical positions offsets..offsets+S (None = 0, whole-prompt
    prefill). Chunked rows attend to the already-written cache through a
    gathered view; whole-prompt rows attend prompt-locally, identical to
    ``prefill``. Returns (last-chunk-token logits [B,V] f32, cache).
    """
    if attn_fn is not None and offsets is not None:
        raise ValueError("attn_fn applies to whole-prompt prefill only (offsets=None)")
    cos, sin = _rope(cfg)
    x = params["embed"][tokens].astype(cfg.dtype)
    b, s = tokens.shape
    page = cache.page_size
    off = jnp.zeros((b,), jnp.int32) if offsets is None else offsets
    positions = off[:, None] + jnp.arange(s)[None]  # [B,S] logical positions
    row = jnp.arange(b)
    chunked = offsets is not None
    # pages holding THIS chunk's writes: logical pages off//page .. (off+s)//page
    total = off + lengths  # [B] cache length after this chunk
    q4c = isinstance(cache, Q4PagedKVCache)
    quant = q4c or isinstance(cache, QPagedKVCache)
    wpp = write_prompts_paged_q4 if q4c else write_prompts_paged_q
    gkv = gather_kv_q4 if q4c else gather_kv_q
    fq = fake_quant_row_int4 if q4c else fake_quant_row
    out_cls = Q4PagedKVCache if q4c else QPagedKVCache

    def body(x, xs):
        if quant:
            lp, k_layer, ks_l, v_layer, vs_l = xs
        else:
            lp, k_layer, v_layer = xs
        q, k, v = _qkv(cfg, lp, x)
        q = apply_rope(q, positions, cos, sin)
        k = apply_rope(k, positions, cos, sin)
        if chunked:
            if quant:
                k_layer, ks_l = wpp(k_layer, ks_l, pages, k, off)
                v_layer, vs_l = wpp(v_layer, vs_l, pages, v, off)
                gkq, gks = gkv(k_layer, ks_l, pages)
                gvq, gvs = gkv(v_layer, vs_l, pages)
                k_view = dequantize_view(gkq, gks, cfg.dtype)
                v_view = dequantize_view(gvq, gvs, cfg.dtype)
            else:
                k_layer, v_layer = write_prompts_paged(k_layer, v_layer, pages, k, v, off)
                # attend over everything written so far (incl. this chunk)
                k_view, v_view = gather_kv(k_layer, v_layer, pages)
            attn = mha_attention(
                q, k_view.swapaxes(1, 2), v_view.swapaxes(1, 2),
                causal=True, q_offset=off, kv_lengths=total,
            )
        else:
            if quant:
                k_layer, ks_l = wpp(k_layer, ks_l, pages, k)
                v_layer, vs_l = wpp(v_layer, vs_l, pages, v)
                # attend to what the cache STORES (fake-quantized k/v) so a
                # later prefix-cache hit — which reads the quantized pages —
                # is bit-identical to this cold run (kvcache.fake_quant_row
                # / quant.fake_quant_row_int4)
                attn = (attn_fn or mha_attention)(
                    q, fq(k), fq(v),
                    causal=True, kv_lengths=lengths)
            else:
                k_layer, v_layer = write_prompts_paged(k_layer, v_layer, pages, k, v)
                attn = (attn_fn or mha_attention)(q, k, v, causal=True, kv_lengths=lengths)
        x = x + qdot(attn.reshape(b, s, -1), lp["wo"])
        x = x + _mlp(cfg, lp, x)
        return x, (k_layer, ks_l, v_layer, vs_l) if quant else (k_layer, v_layer)

    if quant:
        xs = (params["blocks"], cache.k, cache.ks, cache.v, cache.vs)
        x, (new_k, new_ks, new_v, new_vs) = lax.scan(body, x, xs)
        out_cache = out_cls(k=new_k, v=new_v, ks=new_ks, vs=new_vs)
    else:
        x, (new_k, new_v) = lax.scan(body, x, (params["blocks"], cache.k, cache.v))
        out_cache = PagedKVCache(k=new_k, v=new_v)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    last = x[row, lengths - 1]  # [B,E]
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = qdot(last, head).astype(jnp.float32)
    if adapters is not None:
        logits = logits + lora_logits_delta(last, adapters)
    return logits, out_cache


@partial(jax.jit, static_argnums=0, donate_argnums=4)
def decode_step_paged(
    cfg: LlamaConfig, params: dict, tokens: jnp.ndarray, positions: jnp.ndarray,
    cache: PagedKVCache, table: jnp.ndarray, adapters=None,
) -> tuple[jnp.ndarray, PagedKVCache]:
    """One decode step over every slot, K/V appended through the block
    table. Contract matches ``decode_step`` with ``table`` [N, MaxP]."""
    cos, sin = _rope(cfg)
    x = params["embed"][tokens].astype(cfg.dtype)  # [N,E]
    n = tokens.shape[0]
    pos1 = positions[:, None]
    q4c = isinstance(cache, Q4PagedKVCache)
    quant = q4c or isinstance(cache, QPagedKVCache)
    atp = append_tokens_paged_q4 if q4c else append_tokens_paged_q
    pda = paged_decode_attention_q4 if q4c else paged_decode_attention_q
    out_cls = Q4PagedKVCache if q4c else QPagedKVCache

    def body(x, xs):
        if quant:
            lp, k_layer, ks_l, v_layer, vs_l = xs
        else:
            lp, k_layer, v_layer = xs
        q, k, v = _qkv(cfg, lp, x[:, None])
        q = apply_rope(q, pos1, cos, sin)[:, 0]
        k = apply_rope(k, pos1, cos, sin)[:, 0]
        v = v[:, 0]
        if quant:
            k_layer, ks_l = atp(k_layer, ks_l, table, positions, k)
            v_layer, vs_l = atp(v_layer, vs_l, table, positions, v)
            attn = pda(
                q, k_layer, v_layer, ks_l, vs_l, table, positions + 1)
        else:
            k_layer, v_layer = append_tokens_paged(k_layer, v_layer, table, positions, k, v)
            attn = paged_decode_attention(q, k_layer, v_layer, table, positions + 1)
        x = x + qdot(attn.reshape(n, -1), lp["wo"])
        x = x + _mlp(cfg, lp, x)
        return x, (k_layer, ks_l, v_layer, vs_l) if quant else (k_layer, v_layer)

    if quant:
        xs = (params["blocks"], cache.k, cache.ks, cache.v, cache.vs)
        x, (new_k, new_ks, new_v, new_vs) = lax.scan(body, x, xs)
        out_cache = out_cls(k=new_k, v=new_v, ks=new_ks, vs=new_vs)
    else:
        x, (new_k, new_v) = lax.scan(body, x, (params["blocks"], cache.k, cache.v))
        out_cache = PagedKVCache(k=new_k, v=new_v)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = qdot(x, head).astype(jnp.float32)
    if adapters is not None:
        logits = logits + lora_logits_delta(x, adapters)
    return logits, out_cache
