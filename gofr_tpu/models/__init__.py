"""Model families (functional JAX modules — see gofr_tpu.models.base).

The reference has no model layer (SURVEY.md §2.9); this package is the new
capability the TPU build adds: decoder LMs for /generate, encoders for
embedding and classification endpoints, all shardable via logical axes.
"""

from gofr_tpu.models import bert, gpt2, llama, mixtral, vit
from gofr_tpu.models.base import (
    ModelSpec,
    cast_floats,
    get_family,
    param_bytes,
    param_count,
    register_family,
)
from gofr_tpu.models.gpt2 import GPT2Config
from gofr_tpu.models.llama import LlamaConfig
from gofr_tpu.models.mixtral import MixtralConfig
from gofr_tpu.models.bert import BertConfig
from gofr_tpu.models.vit import ViTConfig

register_family("gpt2", gpt2)
register_family("llama", llama)
register_family("mixtral", mixtral)
register_family("bert", bert)
register_family("vit", vit)

__all__ = [
    "ModelSpec",
    "LlamaConfig",
    "MixtralConfig",
    "BertConfig",
    "ViTConfig",
    "gpt2",
    "GPT2Config",
    "llama",
    "mixtral",
    "bert",
    "vit",
    "cast_floats",
    "get_family",
    "param_bytes",
    "param_count",
    "register_family",
]
