"""Vision Transformer classifier (BASELINE.md config #3: pubsub → ViT).

Pre-LayerNorm encoder matching HF ``ViTModel``/``ViTForImageClassification``
numerics. Patch embedding is an unfold + matmul (not a conv): identical
math, and a single large [B*N, P²C] × [P²C, E] matmul maps straight onto
the MXU.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from gofr_tpu.models.base import fan_in_init, truncated_normal
from gofr_tpu.ops import layer_norm, mha_attention


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_channels: int = 3
    hidden_size: int = 1024
    intermediate_size: int = 4096
    num_layers: int = 24
    num_heads: int = 16
    num_classes: int = 1000
    norm_eps: float = 1e-12
    dtype: Any = jnp.float32

    @property
    def head_size(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @classmethod
    def large(cls, **kw) -> "ViTConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "ViTConfig":
        return cls(**{**dict(
            image_size=32, patch_size=8, hidden_size=32, intermediate_size=64,
            num_layers=2, num_heads=2, num_classes=10,
        ), **kw})


def init(cfg: ViTConfig, key: jax.Array) -> dict:
    e, m, nl = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    patch_dim = cfg.patch_size * cfg.patch_size * cfg.num_channels
    ks = jax.random.split(key, 12)
    dt = cfg.dtype
    params = {
        "cls_token": jnp.zeros((1, e), dt),
        "pos_embed": truncated_normal(ks[0], (cfg.num_patches + 1, e), 0.02, dt),
        "patch_w": fan_in_init(ks[1], (patch_dim, e), fan_in=patch_dim, dtype=dt),
        "patch_b": jnp.zeros((e,), dt),
        "blocks": {
            "norm1_w": jnp.ones((nl, e), dt), "norm1_b": jnp.zeros((nl, e), dt),
            "wq": fan_in_init(ks[2], (nl, e, e), fan_in=e, dtype=dt), "bq": jnp.zeros((nl, e), dt),
            "wk": fan_in_init(ks[3], (nl, e, e), fan_in=e, dtype=dt), "bk": jnp.zeros((nl, e), dt),
            "wv": fan_in_init(ks[4], (nl, e, e), fan_in=e, dtype=dt), "bv": jnp.zeros((nl, e), dt),
            "wo": fan_in_init(ks[5], (nl, e, e), fan_in=e, dtype=dt), "bo": jnp.zeros((nl, e), dt),
            "norm2_w": jnp.ones((nl, e), dt), "norm2_b": jnp.zeros((nl, e), dt),
            "w_inter": fan_in_init(ks[6], (nl, e, m), fan_in=e, dtype=dt), "b_inter": jnp.zeros((nl, m), dt),
            "w_out": fan_in_init(ks[7], (nl, m, e), fan_in=m, dtype=dt), "b_out": jnp.zeros((nl, e), dt),
        },
        "final_norm_w": jnp.ones((e,), dt),
        "final_norm_b": jnp.zeros((e,), dt),
    }
    if cfg.num_classes:
        params["head_w"] = fan_in_init(ks[8], (e, cfg.num_classes), fan_in=e, dtype=dt)
        params["head_b"] = jnp.zeros((cfg.num_classes,), dt)
    return params


def param_axes(cfg: ViTConfig) -> dict:
    vec = ("layers", None)
    axes = {
        "cls_token": (None, "embed"),
        "pos_embed": (None, "embed"),
        "patch_w": (None, "embed"),
        "patch_b": ("embed",),
        "blocks": {
            "norm1_w": vec, "norm1_b": vec,
            "wq": ("layers", "embed", "heads"), "bq": ("layers", "heads"),
            "wk": ("layers", "embed", "heads"), "bk": ("layers", "heads"),
            "wv": ("layers", "embed", "heads"), "bv": ("layers", "heads"),
            "wo": ("layers", "heads", "embed"), "bo": vec,
            "norm2_w": vec, "norm2_b": vec,
            "w_inter": ("layers", "embed", "mlp"), "b_inter": ("layers", "mlp"),
            "w_out": ("layers", "mlp", "embed"), "b_out": vec,
        },
        "final_norm_w": (None,),
        "final_norm_b": (None,),
    }
    if cfg.num_classes:
        axes["head_w"] = ("embed", "vocab")
        axes["head_b"] = ("vocab",)
    return axes


def patchify(cfg: ViTConfig, images: jnp.ndarray) -> jnp.ndarray:
    """images [B,H,W,C] → patches [B, N, P*P*C] (row-major within patch,
    matching the transposed HF conv kernel in convert.vit_from_hf)."""
    b, h, w, c = images.shape
    p = cfg.patch_size
    x = images.reshape(b, h // p, p, w // p, p, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # [B, H/P, W/P, P, P, C]
    return x.reshape(b, (h // p) * (w // p), p * p * c)


@partial(jax.jit, static_argnums=0)
def forward(cfg: ViTConfig, params: dict, images: jnp.ndarray) -> jnp.ndarray:
    """images [B,H,W,C] → logits [B,num_classes] (or CLS embedding [B,E]
    when the config has no head)."""
    b = images.shape[0]
    patches = patchify(cfg, images).astype(cfg.dtype)
    x = patches @ params["patch_w"] + params["patch_b"]  # [B,N,E]
    cls = jnp.broadcast_to(params["cls_token"][None], (b, 1, cfg.hidden_size)).astype(cfg.dtype)
    x = jnp.concatenate([cls, x], axis=1) + params["pos_embed"][None]
    s = x.shape[1]

    def body(x, lp):
        h = layer_norm(x, lp["norm1_w"], lp["norm1_b"], cfg.norm_eps)
        q = (h @ lp["wq"] + lp["bq"]).reshape(b, s, cfg.num_heads, cfg.head_size)
        k = (h @ lp["wk"] + lp["bk"]).reshape(b, s, cfg.num_heads, cfg.head_size)
        v = (h @ lp["wv"] + lp["bv"]).reshape(b, s, cfg.num_heads, cfg.head_size)
        attn = mha_attention(q, k, v, causal=False).reshape(b, s, -1)
        x = x + attn @ lp["wo"] + lp["bo"]
        h2 = layer_norm(x, lp["norm2_w"], lp["norm2_b"], cfg.norm_eps)
        inter = jax.nn.gelu(h2 @ lp["w_inter"] + lp["b_inter"], approximate=False)
        x = x + inter @ lp["w_out"] + lp["b_out"]
        return x, None

    x, _ = lax.scan(body, x, params["blocks"])
    x = layer_norm(x, params["final_norm_w"], params["final_norm_b"], cfg.norm_eps)
    cls_out = x[:, 0].astype(jnp.float32)
    if cfg.num_classes:
        return cls_out @ params["head_w"].astype(jnp.float32) + params["head_b"].astype(jnp.float32)
    return cls_out
