"""HuggingFace checkpoint conversion.

Loads a ``transformers`` model (CPU torch) and re-lays its weights into
gofr_tpu's stacked-layer functional pytrees. This is both the production
weight-loading path (serve any HF Llama/BERT/ViT checkpoint) and the
correctness oracle for tests (tiny random HF model → convert → compare
logits).

All torch→numpy→jax copying happens host-side; shard placement is applied
afterwards by the parallel layer (``shard_pytree``), so a 70B checkpoint
can stream straight into sharded device buffers.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np


def _np(t) -> np.ndarray:
    return t.detach().to("cpu").float().numpy()


def _stack(sd: dict, fmt: str, n: int, transpose: bool = False) -> np.ndarray:
    mats = [_np(sd[fmt.format(i=i)]) for i in range(n)]
    if transpose:
        mats = [m.T for m in mats]
    return np.stack(mats)


def _load_hf(model_or_path: Any, *auto_classes: str):
    """Return the model object, loading from a path with the first auto
    class that succeeds (e.g. ImageClassification before bare AutoModel so
    classifier heads survive)."""
    if hasattr(model_or_path, "state_dict"):
        return model_or_path
    import transformers

    last_err: Exception | None = None
    for name in auto_classes:
        try:
            return getattr(transformers, name).from_pretrained(model_or_path)
        except (ValueError, OSError, KeyError) as e:
            last_err = e
    raise ValueError(f"could not load {model_or_path!r} via {auto_classes}") from last_err


# -- Llama ---------------------------------------------------------------------


def llama_from_hf(model_or_path: Any, dtype=jnp.bfloat16):
    """→ (LlamaConfig, params) from an HF ``LlamaForCausalLM`` (or path)."""
    from gofr_tpu.models.llama import LlamaConfig

    hf = _load_hf(model_or_path, "AutoModelForCausalLM")
    hc = hf.config
    tied = bool(getattr(hc, "tie_word_embeddings", False))
    cfg = LlamaConfig(
        vocab_size=hc.vocab_size,
        hidden_size=hc.hidden_size,
        intermediate_size=hc.intermediate_size,
        num_layers=hc.num_hidden_layers,
        num_heads=hc.num_attention_heads,
        num_kv_heads=getattr(hc, "num_key_value_heads", hc.num_attention_heads),
        head_dim=getattr(hc, "head_dim", None),
        rope_theta=getattr(hc, "rope_theta", 10000.0),
        max_seq_len=hc.max_position_embeddings,
        norm_eps=hc.rms_norm_eps,
        tie_embeddings=tied,
        dtype=dtype,
    )
    sd = hf.state_dict()
    nl = cfg.num_layers
    p = "model.layers.{i}."
    params = {
        "embed": jnp.asarray(_np(sd["model.embed_tokens.weight"]), dtype),
        "blocks": {
            "attn_norm": jnp.asarray(_stack(sd, p + "input_layernorm.weight", nl), dtype),
            "wq": jnp.asarray(_stack(sd, p + "self_attn.q_proj.weight", nl, transpose=True), dtype),
            "wk": jnp.asarray(_stack(sd, p + "self_attn.k_proj.weight", nl, transpose=True), dtype),
            "wv": jnp.asarray(_stack(sd, p + "self_attn.v_proj.weight", nl, transpose=True), dtype),
            "wo": jnp.asarray(_stack(sd, p + "self_attn.o_proj.weight", nl, transpose=True), dtype),
            "mlp_norm": jnp.asarray(_stack(sd, p + "post_attention_layernorm.weight", nl), dtype),
            "w_gate": jnp.asarray(_stack(sd, p + "mlp.gate_proj.weight", nl, transpose=True), dtype),
            "w_up": jnp.asarray(_stack(sd, p + "mlp.up_proj.weight", nl, transpose=True), dtype),
            "w_down": jnp.asarray(_stack(sd, p + "mlp.down_proj.weight", nl, transpose=True), dtype),
        },
        "final_norm": jnp.asarray(_np(sd["model.norm.weight"]), dtype),
    }
    if not tied:
        params["lm_head"] = jnp.asarray(_np(sd["lm_head.weight"]).T, dtype)
    return cfg, params


# -- BERT ----------------------------------------------------------------------


def bert_from_hf(model_or_path: Any, dtype=jnp.float32):
    """→ (BertConfig, params) from an HF ``BertModel`` (or path)."""
    from gofr_tpu.models.bert import BertConfig

    hf = _load_hf(model_or_path, "AutoModel")
    hc = hf.config
    cfg = BertConfig(
        vocab_size=hc.vocab_size,
        hidden_size=hc.hidden_size,
        intermediate_size=hc.intermediate_size,
        num_layers=hc.num_hidden_layers,
        num_heads=hc.num_attention_heads,
        max_seq_len=hc.max_position_embeddings,
        type_vocab_size=hc.type_vocab_size,
        norm_eps=hc.layer_norm_eps,
        dtype=dtype,
    )
    sd = {k.removeprefix("bert."): v for k, v in hf.state_dict().items()}
    nl = cfg.num_layers
    p = "encoder.layer.{i}."
    params = {
        "word_embed": jnp.asarray(_np(sd["embeddings.word_embeddings.weight"]), dtype),
        "pos_embed": jnp.asarray(_np(sd["embeddings.position_embeddings.weight"]), dtype),
        "type_embed": jnp.asarray(_np(sd["embeddings.token_type_embeddings.weight"]), dtype),
        "embed_norm_w": jnp.asarray(_np(sd["embeddings.LayerNorm.weight"]), dtype),
        "embed_norm_b": jnp.asarray(_np(sd["embeddings.LayerNorm.bias"]), dtype),
        "blocks": {
            "wq": jnp.asarray(_stack(sd, p + "attention.self.query.weight", nl, transpose=True), dtype),
            "bq": jnp.asarray(_stack(sd, p + "attention.self.query.bias", nl), dtype),
            "wk": jnp.asarray(_stack(sd, p + "attention.self.key.weight", nl, transpose=True), dtype),
            "bk": jnp.asarray(_stack(sd, p + "attention.self.key.bias", nl), dtype),
            "wv": jnp.asarray(_stack(sd, p + "attention.self.value.weight", nl, transpose=True), dtype),
            "bv": jnp.asarray(_stack(sd, p + "attention.self.value.bias", nl), dtype),
            "wo": jnp.asarray(_stack(sd, p + "attention.output.dense.weight", nl, transpose=True), dtype),
            "bo": jnp.asarray(_stack(sd, p + "attention.output.dense.bias", nl), dtype),
            "attn_norm_w": jnp.asarray(_stack(sd, p + "attention.output.LayerNorm.weight", nl), dtype),
            "attn_norm_b": jnp.asarray(_stack(sd, p + "attention.output.LayerNorm.bias", nl), dtype),
            "w_inter": jnp.asarray(_stack(sd, p + "intermediate.dense.weight", nl, transpose=True), dtype),
            "b_inter": jnp.asarray(_stack(sd, p + "intermediate.dense.bias", nl), dtype),
            "w_out": jnp.asarray(_stack(sd, p + "output.dense.weight", nl, transpose=True), dtype),
            "b_out": jnp.asarray(_stack(sd, p + "output.dense.bias", nl), dtype),
            "mlp_norm_w": jnp.asarray(_stack(sd, p + "output.LayerNorm.weight", nl), dtype),
            "mlp_norm_b": jnp.asarray(_stack(sd, p + "output.LayerNorm.bias", nl), dtype),
        },
    }
    if "pooler.dense.weight" in sd:
        params["pooler_w"] = jnp.asarray(_np(sd["pooler.dense.weight"]).T, dtype)
        params["pooler_b"] = jnp.asarray(_np(sd["pooler.dense.bias"]), dtype)
    return cfg, params


# -- ViT -----------------------------------------------------------------------


def vit_from_hf(model_or_path: Any, dtype=jnp.float32):
    """→ (ViTConfig, params) from an HF ``ViTForImageClassification`` or
    ``ViTModel`` (or path)."""
    from gofr_tpu.models.vit import ViTConfig

    hf = _load_hf(model_or_path, "AutoModelForImageClassification", "AutoModel")
    hc = hf.config
    num_classes = getattr(hc, "num_labels", 0)
    sd = hf.state_dict()
    has_head = "classifier.weight" in sd
    cfg = ViTConfig(
        image_size=hc.image_size,
        patch_size=hc.patch_size,
        num_channels=hc.num_channels,
        hidden_size=hc.hidden_size,
        intermediate_size=hc.intermediate_size,
        num_layers=hc.num_hidden_layers,
        num_heads=hc.num_attention_heads,
        num_classes=num_classes if has_head else 0,
        norm_eps=hc.layer_norm_eps,
        dtype=dtype,
    )
    sd = {k.removeprefix("vit."): v for k, v in sd.items()}
    nl = cfg.num_layers
    p = "encoder.layer.{i}."
    # HF patch conv kernel: [E, C, P, P] → matmul layout [C*P*P → P*P*C? ]
    # We unfold patches as [.., P, P, C] flattened row-major, so kernel must
    # be [P*P*C, E] with matching order: transpose conv kernel to [P, P, C, E].
    conv = _np(sd["embeddings.patch_embeddings.projection.weight"])  # [E,C,P,P]
    conv = conv.transpose(2, 3, 1, 0).reshape(-1, cfg.hidden_size)  # [P*P*C, E]
    params = {
        "cls_token": jnp.asarray(_np(sd["embeddings.cls_token"])[0], dtype),  # [1,E]
        "pos_embed": jnp.asarray(_np(sd["embeddings.position_embeddings"])[0], dtype),  # [N+1,E]
        "patch_w": jnp.asarray(conv, dtype),
        "patch_b": jnp.asarray(_np(sd["embeddings.patch_embeddings.projection.bias"]), dtype),
        "blocks": {
            "norm1_w": jnp.asarray(_stack(sd, p + "layernorm_before.weight", nl), dtype),
            "norm1_b": jnp.asarray(_stack(sd, p + "layernorm_before.bias", nl), dtype),
            "wq": jnp.asarray(_stack(sd, p + "attention.attention.query.weight", nl, transpose=True), dtype),
            "bq": jnp.asarray(_stack(sd, p + "attention.attention.query.bias", nl), dtype),
            "wk": jnp.asarray(_stack(sd, p + "attention.attention.key.weight", nl, transpose=True), dtype),
            "bk": jnp.asarray(_stack(sd, p + "attention.attention.key.bias", nl), dtype),
            "wv": jnp.asarray(_stack(sd, p + "attention.attention.value.weight", nl, transpose=True), dtype),
            "bv": jnp.asarray(_stack(sd, p + "attention.attention.value.bias", nl), dtype),
            "wo": jnp.asarray(_stack(sd, p + "attention.output.dense.weight", nl, transpose=True), dtype),
            "bo": jnp.asarray(_stack(sd, p + "attention.output.dense.bias", nl), dtype),
            "norm2_w": jnp.asarray(_stack(sd, p + "layernorm_after.weight", nl), dtype),
            "norm2_b": jnp.asarray(_stack(sd, p + "layernorm_after.bias", nl), dtype),
            "w_inter": jnp.asarray(_stack(sd, p + "intermediate.dense.weight", nl, transpose=True), dtype),
            "b_inter": jnp.asarray(_stack(sd, p + "intermediate.dense.bias", nl), dtype),
            "w_out": jnp.asarray(_stack(sd, p + "output.dense.weight", nl, transpose=True), dtype),
            "b_out": jnp.asarray(_stack(sd, p + "output.dense.bias", nl), dtype),
        },
        "final_norm_w": jnp.asarray(_np(sd["layernorm.weight"]), dtype),
        "final_norm_b": jnp.asarray(_np(sd["layernorm.bias"]), dtype),
    }
    if has_head:
        params["head_w"] = jnp.asarray(_np(sd["classifier.weight"]).T, dtype)
        params["head_b"] = jnp.asarray(_np(sd["classifier.bias"]), dtype)
    return cfg, params


# -- GPT-2 ---------------------------------------------------------------------


def gpt2_from_hf(model_or_path: Any, dtype=jnp.float32):
    """→ (GPT2Config, params) from an HF ``GPT2LMHeadModel`` (or path).

    HF GPT-2 uses Conv1D modules whose weights are stored [in, out] — the
    same convention as this package's matmuls, so no transposes; the fused
    c_attn [E, 3E] splits into wq/wk/wv columns.
    """
    from gofr_tpu.models.gpt2 import GPT2Config

    hf = _load_hf(model_or_path, "AutoModelForCausalLM")
    hc = hf.config
    if getattr(hc, "activation_function", "gelu_new") not in ("gelu_new",):
        raise ValueError(
            f"gpt2_from_hf supports activation_function='gelu_new' only, "
            f"got {hc.activation_function!r} (forward uses approximate gelu)"
        )
    if getattr(hc, "n_inner", None) not in (None, 4 * hc.n_embd):
        raise ValueError(
            f"gpt2_from_hf supports n_inner == 4*n_embd only, got {hc.n_inner}"
        )
    if (not getattr(hc, "scale_attn_weights", True)
            or getattr(hc, "scale_attn_by_inverse_layer_idx", False)
            or getattr(hc, "reorder_and_upcast_attn", False)):
        raise ValueError(
            "gpt2_from_hf supports standard 1/sqrt(d) attention scaling only "
            "(scale_attn_weights=True, no inverse-layer-idx scaling or "
            "reorder_and_upcast_attn) — this checkpoint would silently diverge"
        )
    cfg = GPT2Config(
        vocab_size=hc.vocab_size,
        hidden_size=hc.n_embd,
        num_layers=hc.n_layer,
        num_heads=hc.n_head,
        max_seq_len=hc.n_positions,
        norm_eps=hc.layer_norm_epsilon,
        dtype=dtype,
    )
    sd = hf.state_dict()
    p = "transformer.h.{i}."
    nl, e = hc.n_layer, hc.n_embd
    cattn = _stack(sd, p + "attn.c_attn.weight", nl)   # [L, E, 3E]
    cattn_b = _stack(sd, p + "attn.c_attn.bias", nl)   # [L, 3E]
    params = {
        "wte": jnp.asarray(_np(sd["transformer.wte.weight"]), dtype),
        "wpe": jnp.asarray(_np(sd["transformer.wpe.weight"]), dtype),
        "blocks": {
            "ln1_g": jnp.asarray(_stack(sd, p + "ln_1.weight", nl), dtype),
            "ln1_b": jnp.asarray(_stack(sd, p + "ln_1.bias", nl), dtype),
            "wq": jnp.asarray(cattn[:, :, :e], dtype),
            "bq": jnp.asarray(cattn_b[:, :e], dtype),
            "wk": jnp.asarray(cattn[:, :, e:2 * e], dtype),
            "bk": jnp.asarray(cattn_b[:, e:2 * e], dtype),
            "wv": jnp.asarray(cattn[:, :, 2 * e:], dtype),
            "bv": jnp.asarray(cattn_b[:, 2 * e:], dtype),
            "wo": jnp.asarray(_stack(sd, p + "attn.c_proj.weight", nl), dtype),
            "bo": jnp.asarray(_stack(sd, p + "attn.c_proj.bias", nl), dtype),
            "ln2_g": jnp.asarray(_stack(sd, p + "ln_2.weight", nl), dtype),
            "ln2_b": jnp.asarray(_stack(sd, p + "ln_2.bias", nl), dtype),
            "w_fc": jnp.asarray(_stack(sd, p + "mlp.c_fc.weight", nl), dtype),
            "b_fc": jnp.asarray(_stack(sd, p + "mlp.c_fc.bias", nl), dtype),
            "w_proj": jnp.asarray(_stack(sd, p + "mlp.c_proj.weight", nl), dtype),
            "b_proj": jnp.asarray(_stack(sd, p + "mlp.c_proj.bias", nl), dtype),
        },
        "lnf_g": jnp.asarray(_np(sd["transformer.ln_f.weight"]), dtype),
        "lnf_b": jnp.asarray(_np(sd["transformer.ln_f.bias"]), dtype),
    }
    return cfg, params
