"""WebSocket support: per-message handler loop + thread-safe connection hub.

Parity with gofr `pkg/gofr/websocket.go` + `pkg/gofr/websocket/`: a route
upgrades, the user handler runs once per received message with a Context whose
``bind`` reads that message (`websocket/websocket.go:63-77`), the return value
is written back, and live connections are tracked in a hub keyed by connection
id (`websocket/websocket.go:88-137`) for server-push broadcast.
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Any

from gofr_tpu.utils import bind as binder


class WSConnection:
    """Request implementation over a single received websocket message."""

    def __init__(self, conn_id: str, ws, message: str | bytes, loop: asyncio.AbstractEventLoop):
        self.conn_id = conn_id
        self._ws = ws
        self._message = message
        self._loop = loop
        self._ctx: dict[str, Any] = {}

    # -- Request interface -----------------------------------------------------

    def param(self, key: str) -> str:
        return ""

    def params(self, key: str) -> list[str]:
        return []

    def path_param(self, key: str) -> str:
        return ""

    def bind(self, target: Any = dict) -> Any:
        raw = self._message if isinstance(self._message, bytes) else self._message.encode()
        if target is bytes:
            return raw
        if target is str:
            return raw.decode()
        try:
            data = json.loads(raw)
        except json.JSONDecodeError as e:
            raise binder.BindError("websocket message is not JSON") from e
        return binder.bind(data, target)

    def host_name(self) -> str:
        return "websocket"

    def context(self) -> dict[str, Any]:
        return self._ctx

    # -- push (safe from any thread) ------------------------------------------

    def send(self, data: Any) -> None:
        payload = data if isinstance(data, str) else json.dumps(data, default=str)
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is self._loop:
            # called from an async handler on the serving loop: blocking here
            # would deadlock — schedule the send instead
            self._loop.create_task(self._ws.send_str(payload))
        else:
            asyncio.run_coroutine_threadsafe(self._ws.send_str(payload), self._loop).result(timeout=30)


class ConnectionHub:
    """Thread-safe registry of live websocket connections."""

    def __init__(self):
        self._conns: dict[str, Any] = {}
        self._lock = threading.Lock()

    def add(self, conn_id: str, ws) -> None:
        with self._lock:
            self._conns[conn_id] = ws

    def remove(self, conn_id: str) -> None:
        with self._lock:
            self._conns.pop(conn_id, None)

    def get(self, conn_id: str):
        with self._lock:
            return self._conns.get(conn_id)

    def ids(self) -> list[str]:
        with self._lock:
            return list(self._conns)

    def __len__(self) -> int:
        with self._lock:
            return len(self._conns)

    async def broadcast(self, data: Any) -> int:
        payload = data if isinstance(data, str) else json.dumps(data, default=str)
        with self._lock:
            conns = list(self._conns.values())
        sent = 0
        for ws in conns:
            try:
                await ws.send_str(payload)
                sent += 1
            except Exception:  # noqa: BLE001 - dead conns are reaped by their own loop
                pass
        return sent
