"""Clickhouse datasource plugin (gofr `pkg/gofr/datasource/clickhouse/`,
separate-module tier — SURVEY.md §2.4).

Exec / Select / AsyncInsert surface (`clickhouse.go`) over an injectable
``client_factory``; connection-pool gauges pushed on health checks
(`clickhouse.go:62-66` analog). ``InMemoryClickhouse`` reuses the sqlite
engine underneath for a hermetic, SQL-true fake.
"""

from __future__ import annotations

import time
from typing import Any, Callable

from gofr_tpu.datasource import DatasourceError


class Clickhouse:
    def __init__(
        self,
        dsn: str | None = None,
        client_factory: Callable[..., Any] | None = None,
    ):
        self._dsn = dsn
        self._client_factory = client_factory
        self._client = None
        self.logger = None
        self.metrics = None

    # -- provider lifecycle ----------------------------------------------------

    def use_logger(self, logger) -> None:
        self.logger = logger

    def use_metrics(self, metrics) -> None:
        self.metrics = metrics
        try:
            metrics.new_histogram(
                "app_clickhouse_stats", "clickhouse query duration (µs)",
                buckets=[50, 200, 1000, 5000, 20000, 100000, 500000],
            )
        except Exception:  # noqa: BLE001
            pass

    def connect(self) -> None:
        factory = self._client_factory
        if factory is None:
            try:
                import clickhouse_connect  # type: ignore[import-not-found]
            except ImportError as e:
                raise DatasourceError(e, "clickhouse-connect not installed; pass client_factory") from e

            def factory(dsn):  # noqa: F811
                return clickhouse_connect.get_client(dsn=dsn)

        self._client = factory(self._dsn)
        if self.logger:
            self.logger.info("connected to clickhouse")

    # -- operations ------------------------------------------------------------

    def _observe(self, stmt: str, start: float) -> None:
        micros = (time.perf_counter() - start) * 1e6
        if self.metrics:
            self.metrics.record_histogram("app_clickhouse_stats", micros)
        if self.logger:
            self.logger.debug({"type": "clickhouse", "query": stmt[:120],
                               "duration_us": round(micros, 1)})

    def _run(self, stmt: str, fn: Callable[[Any], Any]) -> Any:
        if self._client is None:
            raise DatasourceError("clickhouse not connected", "call connect() first")
        start = time.perf_counter()
        try:
            return fn(self._client)
        except DatasourceError:
            raise
        except Exception as e:  # noqa: BLE001
            raise DatasourceError(e, f"clickhouse query failed: {stmt[:120]}") from e
        finally:
            self._observe(stmt, start)

    def exec(self, stmt: str, *params: Any) -> None:
        self._run(stmt, lambda c: c.command(stmt, parameters=params or None))

    def select(self, stmt: str, *params: Any) -> list[dict]:
        def go(c):
            res = c.query(stmt, parameters=params or None)
            cols = res.column_names
            return [dict(zip(cols, row)) for row in res.result_rows]

        return self._run(stmt, go)

    def async_insert(self, table: str, rows: list[dict]) -> None:
        """Fire-and-forget batch insert (`AsyncInsert` parity)."""
        if not rows:
            return
        cols = list(rows[0].keys())

        def go(c):
            c.insert(table, [[r.get(k) for k in cols] for r in rows], column_names=cols)

        self._run(f"INSERT INTO {table}", go)

    def health_check(self) -> dict[str, Any]:
        if self._client is None:
            return {"status": "DOWN", "details": {"error": "not connected"}}
        try:
            self._run("SELECT 1", lambda c: c.command("SELECT 1"))
            return {"status": "UP", "details": {}}
        except Exception as e:  # noqa: BLE001
            return {"status": "DOWN", "details": {"error": str(e)}}


# -- in-tree fake (sqlite-backed so SQL actually executes) ---------------------


class InMemoryClickhouseClient:
    def __init__(self, *_a, **_kw):
        import sqlite3

        self._db = sqlite3.connect(":memory:", check_same_thread=False)

    def command(self, stmt: str, parameters=None):
        cur = self._db.execute(stmt, tuple(parameters or ()))
        self._db.commit()
        return cur.fetchone()

    def query(self, stmt: str, parameters=None):
        cur = self._db.execute(stmt, tuple(parameters or ()))

        class _Res:
            column_names = [d[0] for d in cur.description or []]
            result_rows = cur.fetchall()

        return _Res()

    def insert(self, table: str, rows, column_names):
        ph = ",".join("?" for _ in column_names)
        self._db.executemany(
            f"INSERT INTO {table} ({','.join(column_names)}) VALUES ({ph})", rows
        )
        self._db.commit()


def in_memory_clickhouse() -> Clickhouse:
    return Clickhouse(client_factory=lambda *_: InMemoryClickhouseClient())
