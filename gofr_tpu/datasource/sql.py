"""SQL datasource: dialect-aware wrapper with per-query logging + metrics.

Parity with gofr `pkg/gofr/datasource/sql/`: DSN built from ``DB_*`` config with
dialect switch (`sql.go:168-188`), lazy skip when unconfigured (`sql.go:43-46`),
every query wrapped with a debug log + ``app_sql_stats`` histogram
(`db.go:47-105`), transactions, a reflection-free ``select_into`` helper, a
dialect-quoted CRUD query builder (`query_builder.go`), and health checks.

In-tree driver: sqlite3 (stdlib). mysql/postgres engage automatically when
their drivers are importable; otherwise the container logs a warning and leaves
SQL unwired (config-gated feature-off semantics).
"""

from __future__ import annotations

import sqlite3
import threading
import time
from dataclasses import is_dataclass, fields as dc_fields
from typing import Any, Iterable, Sequence

from gofr_tpu.datasource import DatasourceError


class Row(dict):
    """A result row: dict with attribute access."""

    def __getattr__(self, name: str) -> Any:
        try:
            return self[name]
        except KeyError as e:
            raise AttributeError(name) from e


class DB:
    """Thread-safe SQL access with logging + metrics on every call."""

    def __init__(self, conn, dialect: str, logger, metrics, placeholder: str = "?"):
        self._conn = conn
        self.dialect = dialect
        self._logger = logger
        self._metrics = metrics
        self._placeholder = placeholder
        self._lock = threading.RLock()

    # -- core ------------------------------------------------------------------

    def _normalize(self, query: str) -> str:
        # user-facing queries use '?'; translate for drivers with '%s' paramstyle.
        # (literal '?' inside SQL string literals is not supported on those dialects)
        if self._placeholder != "?":
            return query.replace("?", self._placeholder)
        return query

    def _observe(self, kind: str, query: str, start: float) -> None:
        dur = time.perf_counter() - start
        if self._metrics is not None:
            self._metrics.record_histogram("app_sql_stats", dur, type=kind)
        if self._logger is not None:
            self._logger.debug({"message": "sql", "query": query.strip()[:200], "duration_us": int(dur * 1e6), "type": kind})

    def query(self, query: str, params: Sequence[Any] = ()) -> list[Row]:
        start = time.perf_counter()
        with self._lock:
            try:
                cur = self._conn.execute(self._normalize(query), tuple(params))
                cols = [d[0] for d in cur.description] if cur.description else []
                rows = [Row(zip(cols, r)) for r in cur.fetchall()]
                # close the implicit read transaction (postgres would otherwise
                # sit idle-in-transaction; harmless no-op on sqlite)
                self._conn.commit()
            except Exception as e:  # noqa: BLE001
                try:
                    self._conn.rollback()  # clear aborted-transaction state
                except Exception:  # noqa: BLE001
                    pass
                raise DatasourceError(e) from e
        self._observe("query", query, start)
        return rows

    def query_row(self, query: str, params: Sequence[Any] = ()) -> Row | None:
        rows = self.query(query, params)
        return rows[0] if rows else None

    def execute(self, query: str, params: Sequence[Any] = ()) -> int:
        start = time.perf_counter()
        with self._lock:
            try:
                cur = self._conn.execute(self._normalize(query), tuple(params))
                self._conn.commit()
                affected = cur.rowcount
            except Exception as e:  # noqa: BLE001
                self._conn.rollback()
                raise DatasourceError(e) from e
        self._observe("exec", query, start)
        return affected

    def execute_many(self, query: str, seq_of_params: Iterable[Sequence[Any]]) -> int:
        start = time.perf_counter()
        with self._lock:
            try:
                cur = self._conn.executemany(self._normalize(query), [tuple(p) for p in seq_of_params])
                self._conn.commit()
                affected = cur.rowcount
            except Exception as e:  # noqa: BLE001
                self._conn.rollback()
                raise DatasourceError(e) from e
        self._observe("exec_many", query, start)
        return affected

    def select_into(self, cls: type, query: str, params: Sequence[Any] = ()) -> list[Any]:
        """Bind rows into dataclass instances (analog of gofr's reflective Select)."""
        rows = self.query(query, params)
        if not is_dataclass(cls):
            raise DatasourceError(f"select_into target must be a dataclass, got {cls!r}")
        names = {f.name for f in dc_fields(cls)}
        return [cls(**{k: v for k, v in row.items() if k in names}) for row in rows]

    # -- transactions ----------------------------------------------------------

    def begin(self) -> "Tx":
        return Tx(self)

    # -- lifecycle -------------------------------------------------------------

    def health_check(self) -> dict[str, Any]:
        try:
            with self._lock:
                self._conn.execute("SELECT 1")
            return {"status": "UP", "details": {"dialect": self.dialect}}
        except Exception as e:  # noqa: BLE001
            return {"status": "DOWN", "details": {"dialect": self.dialect, "error": str(e)}}

    def close(self) -> None:
        with self._lock:
            self._conn.close()


class Tx:
    """Transaction: all statements commit together or roll back on error."""

    def __init__(self, db: DB):
        self._db = db
        self._done = False

    def __enter__(self) -> "Tx":
        self._db._lock.acquire()
        return self

    def query(self, query: str, params: Sequence[Any] = ()) -> list[Row]:
        cur = self._db._conn.execute(self._db._normalize(query), tuple(params))
        cols = [d[0] for d in cur.description] if cur.description else []
        return [Row(zip(cols, r)) for r in cur.fetchall()]

    def execute(self, query: str, params: Sequence[Any] = ()) -> int:
        return self._db._conn.execute(self._db._normalize(query), tuple(params)).rowcount

    def commit(self) -> None:
        self._db._conn.commit()
        self._done = True

    def rollback(self) -> None:
        self._db._conn.rollback()
        self._done = True

    def __exit__(self, exc_type, exc, tb) -> None:
        try:
            if exc is not None:
                self._db._conn.rollback()
            elif not self._done:
                self._db._conn.commit()
        finally:
            self._db._lock.release()


# -- query builder (gofr `datasource/sql/query_builder.go`) --------------------

_QUOTES = {"mysql": "`", "sqlite": '"', "postgres": '"'}


def quote_ident(name: str, dialect: str) -> str:
    q = _QUOTES.get(dialect, '"')
    safe = "".join(ch for ch in name if ch.isalnum() or ch == "_")
    return f"{q}{safe}{q}"


def insert_query(table: str, columns: Sequence[str], dialect: str) -> str:
    cols = ", ".join(quote_ident(c, dialect) for c in columns)
    ph = ", ".join(["?"] * len(columns))
    return f"INSERT INTO {quote_ident(table, dialect)} ({cols}) VALUES ({ph})"


def select_all_query(table: str, dialect: str) -> str:
    return f"SELECT * FROM {quote_ident(table, dialect)}"


def select_by_query(table: str, key: str, dialect: str) -> str:
    return f"SELECT * FROM {quote_ident(table, dialect)} WHERE {quote_ident(key, dialect)} = ?"


def update_query(table: str, columns: Sequence[str], key: str, dialect: str) -> str:
    sets = ", ".join(f"{quote_ident(c, dialect)} = ?" for c in columns)
    return f"UPDATE {quote_ident(table, dialect)} SET {sets} WHERE {quote_ident(key, dialect)} = ?"


def delete_query(table: str, key: str, dialect: str) -> str:
    return f"DELETE FROM {quote_ident(table, dialect)} WHERE {quote_ident(key, dialect)} = ?"


# -- connection factory --------------------------------------------------------


def connect_sql(config, logger, metrics) -> DB | None:
    dialect = (config.get("DB_DIALECT") or "sqlite").lower()
    if dialect in ("sqlite", "sqlite3"):
        name = config.get_or_default("DB_NAME", ":memory:")
        conn = sqlite3.connect(name, check_same_thread=False)
        conn.execute("PRAGMA journal_mode=WAL") if name != ":memory:" else None
        logger.infof("connected to sqlite database %s", name)
        return DB(conn, "sqlite", logger, metrics)
    if dialect == "mysql":
        try:
            import pymysql  # type: ignore[import-not-found]
        except ImportError:
            logger.warn("DB_DIALECT=mysql but pymysql driver is not installed; SQL not wired")
            return None
        conn = pymysql.connect(
            host=config.get_or_default("DB_HOST", "localhost"),
            port=config.get_int("DB_PORT", 3306),
            user=config.get_or_default("DB_USER", "root"),
            password=config.get_or_default("DB_PASSWORD", ""),
            database=config.get_or_default("DB_NAME", ""),
            autocommit=False,
        )
        return DB(_DBAPIAdapter(conn), "mysql", logger, metrics, placeholder="%s")
    if dialect in ("postgres", "postgresql"):
        try:
            import psycopg2  # type: ignore[import-not-found]
        except ImportError:
            logger.warn("DB_DIALECT=postgres but psycopg2 driver is not installed; SQL not wired")
            return None
        conn = psycopg2.connect(
            host=config.get_or_default("DB_HOST", "localhost"),
            port=config.get_int("DB_PORT", 5432),
            user=config.get_or_default("DB_USER", "postgres"),
            password=config.get_or_default("DB_PASSWORD", ""),
            dbname=config.get_or_default("DB_NAME", "postgres"),
        )
        return DB(_DBAPIAdapter(conn), "postgres", logger, metrics, placeholder="%s")
    logger.warnf("unknown DB_DIALECT %r; SQL not wired", dialect)
    return None


class _DBAPIAdapter:
    """Adapts cursor-style DBAPI drivers to sqlite3's connection.execute style."""

    def __init__(self, conn):
        self._conn = conn

    def execute(self, query: str, params: Sequence[Any] = ()):
        cur = self._conn.cursor()
        cur.execute(query, params)
        return cur

    def executemany(self, query: str, seq: Sequence[Sequence[Any]]):
        cur = self._conn.cursor()
        cur.executemany(query, seq)
        return cur

    def commit(self) -> None:
        self._conn.commit()

    def rollback(self) -> None:
        self._conn.rollback()

    def close(self) -> None:
        self._conn.close()
