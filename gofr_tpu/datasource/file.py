"""File datasource: local filesystem with typed row readers, plus the
remote-filesystem provider seam.

Parity with gofr `pkg/gofr/datasource/file/`: Create/Mkdir/Open/Remove/Rename
surface plus ``read_rows`` returning JSON/CSV/text row iterators selected by
extension (`file/file.go:50-56`). Remote filesystems plug in by implementing
the same methods — the ``FileSystemProvider`` pattern (`file/file.go:69-78`):
``app.add_file_store(provider)`` swaps ``container.file`` for the provider,
wiring its optional ``use_logger``/``use_metrics``/``connect`` hooks exactly
like the external-DB plugins, and handlers keep using ``ctx.file`` unchanged.
``InMemoryFileSystem`` is the in-tree provider fake (the MockPubSub
discipline): a functional remote-FS stand-in tests drive the seam with.
"""

from __future__ import annotations

import csv
import io
import json
import os
import posixpath
import shutil
import time
from typing import Any, Iterator, Protocol, runtime_checkable


@runtime_checkable
class FileSystemProvider(Protocol):
    """The surface ``app.add_file_store`` expects (file.go:69-78 parity).

    Optional plugin hooks — ``use_logger(logger)``, ``use_metrics(metrics)``,
    ``connect()`` — are called at registration when present, in that order
    (the `external_db.go` wiring contract)."""

    def create(self, name: str, data: bytes = b"") -> None: ...

    def read(self, name: str) -> bytes: ...

    def open(self, name: str, mode: str = "rb") -> Any: ...

    def mkdir(self, name: str) -> None: ...

    def mkdir_all(self, name: str) -> None: ...

    def remove(self, name: str) -> None: ...

    def remove_all(self, name: str) -> None: ...

    def rename(self, old: str, new: str) -> None: ...

    def exists(self, name: str) -> bool: ...

    def list(self, name: str = ".") -> list[str]: ...

    def stat(self, name: str) -> Any: ...

    def read_rows(self, name: str) -> Iterator[Any]: ...

    def health_check(self) -> dict[str, Any]: ...


def parse_rows(name: str, data: bytes) -> Iterator[Any]:
    """Extension-dispatched row parsing shared by every provider: dicts for
    .json/.jsonl, dicts for .csv (header row), stripped lines otherwise."""
    ext = os.path.splitext(name)[1].lower()
    if ext == ".json":
        parsed = json.loads(data)
        yield from (parsed if isinstance(parsed, list) else [parsed])
    elif ext == ".jsonl":
        for line in data.splitlines():
            if line.strip():
                yield json.loads(line)
    elif ext == ".csv":
        reader = csv.DictReader(io.StringIO(data.decode()))
        yield from reader
    else:
        for line in data.decode(errors="replace").splitlines():
            yield line


class LocalFileSystem:
    def __init__(self, root: str = "."):
        self.root = root

    def _p(self, name: str) -> str:
        return name if os.path.isabs(name) else os.path.join(self.root, name)

    def create(self, name: str, data: bytes = b"") -> None:
        with open(self._p(name), "wb") as f:
            f.write(data)

    def read(self, name: str) -> bytes:
        with open(self._p(name), "rb") as f:
            return f.read()

    def open(self, name: str, mode: str = "rb"):
        return open(self._p(name), mode)

    def mkdir(self, name: str) -> None:
        os.mkdir(self._p(name))

    def mkdir_all(self, name: str) -> None:
        os.makedirs(self._p(name), exist_ok=True)

    def remove(self, name: str) -> None:
        os.remove(self._p(name))

    def remove_all(self, name: str) -> None:
        shutil.rmtree(self._p(name), ignore_errors=True)

    def rename(self, old: str, new: str) -> None:
        os.replace(self._p(old), self._p(new))

    def exists(self, name: str) -> bool:
        return os.path.exists(self._p(name))

    def list(self, name: str = ".") -> list[str]:
        return sorted(os.listdir(self._p(name)))

    def stat(self, name: str) -> os.stat_result:
        return os.stat(self._p(name))

    # -- row readers (extension-dispatched) ------------------------------------

    def read_rows(self, name: str) -> Iterator[Any]:
        """Yield rows: dicts for .json/.jsonl, dicts for .csv (header row),
        stripped lines for anything else."""
        yield from parse_rows(name, self.read(name))

    def health_check(self) -> dict[str, Any]:
        usage = shutil.disk_usage(self.root)
        return {"status": "UP", "details": {"root": os.path.abspath(self.root), "free_bytes": usage.free}}


class _MemStat:
    """stat()-shaped result for the in-memory provider."""

    __slots__ = ("st_size", "st_mtime", "st_mode")

    def __init__(self, size: int, mtime: float, is_dir: bool):
        self.st_size = size
        self.st_mtime = mtime
        self.st_mode = 0o040755 if is_dir else 0o100644


class InMemoryFileSystem:
    """Remote-FS provider fake: the full ``FileSystemProvider`` surface over
    an in-process dict keyed by normalized POSIX paths, including the plugin
    hooks (``use_logger``/``use_metrics``/``connect``) so the registration
    wiring itself is testable. DOWN until ``connect()`` runs — like a remote
    client before its session is established."""

    def __init__(self, bucket: str = "mem"):
        self.bucket = bucket
        self.files: dict[str, bytes] = {}
        self.dirs: set[str] = {""}
        self.logger = None
        self.metrics = None
        self.connected = False

    # -- plugin hooks (external_db.go wiring contract) -------------------------

    def use_logger(self, logger) -> None:
        self.logger = logger

    def use_metrics(self, metrics) -> None:
        self.metrics = metrics

    def connect(self) -> None:
        self.connected = True
        if self.logger is not None:
            self.logger.infof("connected to in-memory file store %s", self.bucket)

    # -- provider surface ------------------------------------------------------

    @staticmethod
    def _norm(name: str) -> str:
        if name in (".", "", "/"):
            return ""
        path = posixpath.normpath(str(name).replace("\\", "/")).lstrip("/")
        # normpath collapsed interior ".."; clip any still escaping the
        # root. Dotfile names (".env") must survive intact — strip path
        # STRUCTURE only, never characters of a component.
        while path == ".." or path.startswith("../"):
            path = path[2:].lstrip("/")
        return "" if path == "." else path

    def _parent_ok(self, path: str) -> None:
        parent = posixpath.dirname(path)
        if parent and parent not in self.dirs:
            raise FileNotFoundError(f"no such directory: {parent!r}")

    def create(self, name: str, data: bytes = b"") -> None:
        path = self._norm(name)
        self._parent_ok(path)
        self.files[path] = bytes(data)

    def read(self, name: str) -> bytes:
        path = self._norm(name)
        if path not in self.files:
            raise FileNotFoundError(name)
        return self.files[path]

    def open(self, name: str, mode: str = "rb"):
        if "w" in mode or "a" in mode:
            raise NotImplementedError("in-memory provider opens read-only")
        data = self.read(name)
        return io.StringIO(data.decode()) if "b" not in mode else io.BytesIO(data)

    def mkdir(self, name: str) -> None:
        path = self._norm(name)
        if path in self.dirs:
            raise FileExistsError(name)
        self._parent_ok(path)
        self.dirs.add(path)

    def mkdir_all(self, name: str) -> None:
        path = self._norm(name)
        while path:
            self.dirs.add(path)
            path = posixpath.dirname(path)

    def remove(self, name: str) -> None:
        path = self._norm(name)
        if path not in self.files:
            raise FileNotFoundError(name)
        del self.files[path]

    def remove_all(self, name: str) -> None:
        path = self._norm(name)
        self.files = {p: v for p, v in self.files.items()
                      if p != path and not p.startswith(path + "/")}
        self.dirs = {d for d in self.dirs
                     if d != path and not d.startswith(path + "/")}

    def rename(self, old: str, new: str) -> None:
        src, dst = self._norm(old), self._norm(new)
        if src not in self.files:
            raise FileNotFoundError(old)
        self._parent_ok(dst)
        self.files[dst] = self.files.pop(src)

    def exists(self, name: str) -> bool:
        path = self._norm(name)
        return path in self.files or path in self.dirs

    def list(self, name: str = ".") -> list[str]:
        path = self._norm(name)
        if path and path not in self.dirs:
            raise FileNotFoundError(name)
        prefix = path + "/" if path else ""
        out = set()
        for p in list(self.files) + list(self.dirs - {""}):
            if p.startswith(prefix) and p != path:
                out.add(p[len(prefix):].split("/", 1)[0])
        return sorted(out)

    def stat(self, name: str) -> _MemStat:
        path = self._norm(name)
        if path in self.files:
            return _MemStat(len(self.files[path]), time.time(), False)
        if path in self.dirs:
            return _MemStat(0, time.time(), True)
        raise FileNotFoundError(name)

    def read_rows(self, name: str) -> Iterator[Any]:
        yield from parse_rows(name, self.read(name))

    def health_check(self) -> dict[str, Any]:
        if not self.connected:
            return {"status": "DOWN", "details": {"error": "not connected"}}
        return {"status": "UP",
                "details": {"backend": "inmemory-fs", "bucket": self.bucket,
                            "files": len(self.files)}}
