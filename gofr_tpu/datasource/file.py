"""File datasource: local filesystem with typed row readers.

Parity with gofr `pkg/gofr/datasource/file/`: Create/Mkdir/Open/Remove/Rename
surface plus ``read_rows`` returning JSON/CSV/text row iterators selected by
extension (`file/file.go:50-56`). Remote filesystems plug in by implementing
the same methods (FileSystemProvider pattern).
"""

from __future__ import annotations

import csv
import io
import json
import os
import shutil
from typing import Any, Iterator


class LocalFileSystem:
    def __init__(self, root: str = "."):
        self.root = root

    def _p(self, name: str) -> str:
        return name if os.path.isabs(name) else os.path.join(self.root, name)

    def create(self, name: str, data: bytes = b"") -> None:
        with open(self._p(name), "wb") as f:
            f.write(data)

    def read(self, name: str) -> bytes:
        with open(self._p(name), "rb") as f:
            return f.read()

    def open(self, name: str, mode: str = "rb"):
        return open(self._p(name), mode)

    def mkdir(self, name: str) -> None:
        os.mkdir(self._p(name))

    def mkdir_all(self, name: str) -> None:
        os.makedirs(self._p(name), exist_ok=True)

    def remove(self, name: str) -> None:
        os.remove(self._p(name))

    def remove_all(self, name: str) -> None:
        shutil.rmtree(self._p(name), ignore_errors=True)

    def rename(self, old: str, new: str) -> None:
        os.replace(self._p(old), self._p(new))

    def exists(self, name: str) -> bool:
        return os.path.exists(self._p(name))

    def list(self, name: str = ".") -> list[str]:
        return sorted(os.listdir(self._p(name)))

    def stat(self, name: str) -> os.stat_result:
        return os.stat(self._p(name))

    # -- row readers (extension-dispatched) ------------------------------------

    def read_rows(self, name: str) -> Iterator[Any]:
        """Yield rows: dicts for .json/.jsonl, dicts for .csv (header row),
        stripped lines for anything else."""
        ext = os.path.splitext(name)[1].lower()
        data = self.read(name)
        if ext == ".json":
            parsed = json.loads(data)
            yield from (parsed if isinstance(parsed, list) else [parsed])
        elif ext == ".jsonl":
            for line in data.splitlines():
                if line.strip():
                    yield json.loads(line)
        elif ext == ".csv":
            reader = csv.DictReader(io.StringIO(data.decode()))
            yield from reader
        else:
            for line in data.decode(errors="replace").splitlines():
                yield line

    def health_check(self) -> dict[str, Any]:
        usage = shutil.disk_usage(self.root)
        return {"status": "UP", "details": {"root": os.path.abspath(self.root), "free_bytes": usage.free}}
