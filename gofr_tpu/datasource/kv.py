"""KV store: embedded persistent key-value datasource.

Capability parity with the reference's BadgerDB plugin (gofr
`pkg/gofr/datasource/kv-store/badger/`): get/set/delete inside transactions with
an ``app_kv_stats`` histogram per op. Backed by sqlite (stdlib) for durability
without external deps — same WAL-backed embedded-store shape as Badger.
"""

from __future__ import annotations

import sqlite3
import threading
import time
from typing import Any

from gofr_tpu.datasource import DatasourceError


class KVStore:
    def __init__(self, path: str = ":memory:", logger=None, metrics=None):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("CREATE TABLE IF NOT EXISTS kv (k TEXT PRIMARY KEY, v BLOB)")
        self._conn.commit()
        self._logger = logger
        self._metrics = metrics
        self._lock = threading.Lock()
        self.path = path

    def _observe(self, op: str, start: float) -> None:
        if self._metrics is not None:
            self._metrics.record_histogram("app_kv_stats", time.perf_counter() - start, op=op)

    def get(self, key: str) -> bytes | None:
        start = time.perf_counter()
        with self._lock:
            row = self._conn.execute("SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
        self._observe("get", start)
        return row[0] if row else None

    def set(self, key: str, value: Any) -> None:
        data = value if isinstance(value, bytes) else str(value).encode()
        start = time.perf_counter()
        with self._lock:
            try:
                self._conn.execute(
                    "INSERT INTO kv (k, v) VALUES (?, ?) ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                    (key, data),
                )
                self._conn.commit()
            except sqlite3.Error as e:
                self._conn.rollback()
                raise DatasourceError(e) from e
        self._observe("set", start)

    def delete(self, key: str) -> None:
        start = time.perf_counter()
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE k = ?", (key,))
            self._conn.commit()
        self._observe("delete", start)

    def keys(self) -> list[str]:
        with self._lock:
            return [r[0] for r in self._conn.execute("SELECT k FROM kv ORDER BY k").fetchall()]

    def health_check(self) -> dict[str, Any]:
        try:
            with self._lock:
                self._conn.execute("SELECT 1")
            return {"status": "UP", "details": {"path": self.path}}
        except Exception as e:  # noqa: BLE001
            return {"status": "DOWN", "details": {"path": self.path, "error": str(e)}}

    def close(self) -> None:
        with self._lock:
            self._conn.close()
