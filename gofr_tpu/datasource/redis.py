"""Redis datasource: dependency-free RESP2 client with command logging + metrics.

Parity with gofr `pkg/gofr/datasource/redis/`: config from ``REDIS_HOST/PORT``,
5s ping timeout on connect (`redis.go:16-19,47-55`), and every command logged
with µs duration + recorded in ``app_redis_stats`` (`hook.go:17-50`). The wire
protocol is implemented directly (redis-py is not a baked-in dependency), with
pipelining support.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any

from gofr_tpu.datasource import DatasourceError


class RESPConnection:
    def __init__(self, host: str, port: int, timeout: float = 5.0, db: int = 0, password: str | None = None):
        self.host, self.port = host, port
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._buf = b""
        if password:
            self._roundtrip([b"AUTH", password.encode()])
        if db:
            self._roundtrip([b"SELECT", str(db).encode()])

    def _encode(self, parts: list[bytes]) -> bytes:
        out = [b"*%d\r\n" % len(parts)]
        for p in parts:
            out.append(b"$%d\r\n%s\r\n" % (len(p), p))
        return b"".join(out)

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n + 2 :]
        return data

    def _read_reply(self) -> Any:
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise DatasourceError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n == -1:
                return None
            return self._read_exact(n)
        if kind == b"*":
            n = int(rest)
            if n == -1:
                return None
            return [self._read_reply() for _ in range(n)]
        raise DatasourceError(f"unexpected RESP reply type {line!r}")

    def _roundtrip(self, parts: list[bytes]) -> Any:
        self._sock.sendall(self._encode(parts))
        return self._read_reply()

    def send(self, parts: list[bytes]) -> None:
        self._sock.sendall(self._encode(parts))

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _to_bytes(v: Any) -> bytes:
    if isinstance(v, bytes):
        return v
    return str(v).encode()


class Redis:
    """Command API over one connection (thread-safe via lock)."""

    def __init__(self, conn: RESPConnection, logger=None, metrics=None):
        self._conn = conn
        self._logger = logger
        self._metrics = metrics
        self._lock = threading.Lock()

    def command(self, *args: Any) -> Any:
        parts = [_to_bytes(a) for a in args]
        start = time.perf_counter()
        with self._lock:
            result = self._conn._roundtrip(parts)
        dur = time.perf_counter() - start
        if self._metrics is not None:
            self._metrics.record_histogram("app_redis_stats", dur, command=str(args[0]).upper())
        if self._logger is not None:
            self._logger.debug({"message": "redis", "command": str(args[0]).upper(), "duration_us": int(dur * 1e6)})
        return result

    # common command sugar
    def ping(self) -> bool:
        return self.command("PING") == "PONG"

    def get(self, key: str) -> bytes | None:
        return self.command("GET", key)

    def set(self, key: str, value: Any, ex: int | None = None) -> bool:
        args: list[Any] = ["SET", key, value]
        if ex is not None:
            args += ["EX", ex]
        return self.command(*args) == "OK"

    def delete(self, *keys: str) -> int:
        return self.command("DEL", *keys)

    def incr(self, key: str) -> int:
        return self.command("INCR", key)

    def expire(self, key: str, seconds: int) -> int:
        return self.command("EXPIRE", key, seconds)

    def ttl(self, key: str) -> int:
        return self.command("TTL", key)

    def hset(self, key: str, field: str, value: Any) -> int:
        return self.command("HSET", key, field, value)

    def hget(self, key: str, field: str) -> bytes | None:
        return self.command("HGET", key, field)

    def hgetall(self, key: str) -> dict[str, bytes]:
        flat = self.command("HGETALL", key) or []
        return {flat[i].decode(): flat[i + 1] for i in range(0, len(flat), 2)}

    def lpush(self, key: str, *values: Any) -> int:
        return self.command("LPUSH", key, *values)

    def rpop(self, key: str) -> bytes | None:
        return self.command("RPOP", key)

    def keys(self, pattern: str = "*") -> list[bytes]:
        return self.command("KEYS", pattern) or []

    def pipeline(self) -> "Pipeline":
        return Pipeline(self)

    def health_check(self) -> dict[str, Any]:
        try:
            ok = self.ping()
            return {"status": "UP" if ok else "DOWN", "details": {"host": self._conn.host}}
        except Exception as e:  # noqa: BLE001
            return {"status": "DOWN", "details": {"host": self._conn.host, "error": str(e)}}

    def close(self) -> None:
        self._conn.close()


class Pipeline:
    """Batched commands in one roundtrip (logged as one pipeline op)."""

    def __init__(self, redis: Redis):
        self._redis = redis
        self._commands: list[list[bytes]] = []

    def command(self, *args: Any) -> "Pipeline":
        self._commands.append([_to_bytes(a) for a in args])
        return self

    def set(self, key: str, value: Any) -> "Pipeline":
        return self.command("SET", key, value)

    def get(self, key: str) -> "Pipeline":
        return self.command("GET", key)

    def execute(self) -> list[Any]:
        if not self._commands:
            return []
        start = time.perf_counter()
        r = self._redis
        with r._lock:
            for parts in self._commands:
                r._conn.send(parts)
            # drain EVERY reply even on error replies — leaving replies buffered
            # would desync the connection for all later commands
            results: list[Any] = []
            first_error: DatasourceError | None = None
            for _ in self._commands:
                try:
                    results.append(r._conn._read_reply())
                except DatasourceError as e:
                    results.append(e)
                    if first_error is None:
                        first_error = e
        dur = time.perf_counter() - start
        if r._metrics is not None:
            r._metrics.record_histogram("app_redis_stats", dur, command="PIPELINE")
        if r._logger is not None:
            r._logger.debug({"message": "redis pipeline", "commands": len(self._commands), "duration_us": int(dur * 1e6)})
        self._commands = []
        if first_error is not None:
            raise first_error
        return results


def connect_redis(config, logger, metrics) -> Redis | None:
    host = config.get("REDIS_HOST")
    if not host:
        return None
    port = config.get_int("REDIS_PORT", 6379)
    try:
        conn = RESPConnection(
            host, port,
            timeout=config.get_float("REDIS_TIMEOUT", 5.0),
            db=config.get_int("REDIS_DB", 0),
            password=config.get("REDIS_PASSWORD"),
        )
        client = Redis(conn, logger, metrics)
        client.ping()
        logger.infof("connected to redis at %s:%d", host, port)
        return client
    except Exception as e:  # noqa: BLE001
        logger.errorf("could not connect to redis at %s:%d: %s", host, port, e)
        return None
