"""Cassandra datasource plugin (gofr `pkg/gofr/datasource/cassandra/`,
separate-module tier — SURVEY.md §2.4).

The session is reached through an injectable ``session_factory`` (the
reference hides gocql behind `clusterConfig/session/query` interfaces for
exactly this mockability, `cassandra.go:22-26`); ``InMemorySession`` is an
in-tree fake good enough for CRUD-shaped statements. Row binding into
dataclass/dict targets mirrors the reference's reflection row-binding
(`cassandra.go:87-`); ``exec_cas`` is the lightweight-transaction analog.
``app_cassandra_stats`` histogram per query (`cassandra.go:63-64`).
"""

from __future__ import annotations

import dataclasses
import re
import time
from typing import Any, Callable

from gofr_tpu.datasource import DatasourceError


class Cassandra:
    def __init__(
        self,
        hosts: str | None = None,
        keyspace: str = "test",
        session_factory: Callable[..., Any] | None = None,
    ):
        self._hosts = (hosts or "localhost").split(",")
        self._keyspace = keyspace
        self._session_factory = session_factory
        self._session = None
        self.logger = None
        self.metrics = None

    # -- provider lifecycle ----------------------------------------------------

    def use_logger(self, logger) -> None:
        self.logger = logger

    def use_metrics(self, metrics) -> None:
        self.metrics = metrics
        try:
            metrics.new_histogram(
                "app_cassandra_stats", "cassandra query duration (µs)",
                buckets=[50, 200, 1000, 5000, 20000, 100000, 500000],
            )
        except Exception:  # noqa: BLE001
            pass

    def connect(self) -> None:
        factory = self._session_factory
        if factory is None:
            try:
                from cassandra.cluster import Cluster  # type: ignore[import-not-found]
            except ImportError as e:
                raise DatasourceError(e, "cassandra-driver not installed; pass session_factory") from e

            def factory(hosts, keyspace):  # noqa: F811
                return Cluster(hosts).connect(keyspace)

        self._session = factory(self._hosts, self._keyspace)
        if self.logger:
            self.logger.info(f"connected to cassandra keyspace {self._keyspace!r}")

    # -- operations ------------------------------------------------------------

    def _observe(self, stmt: str, start: float) -> None:
        micros = (time.perf_counter() - start) * 1e6
        if self.metrics:
            self.metrics.record_histogram("app_cassandra_stats", micros)
        if self.logger:
            self.logger.debug({"type": "cassandra", "query": stmt[:120],
                               "duration_us": round(micros, 1)})

    def _execute(self, stmt: str, params: tuple = ()) -> Any:
        if self._session is None:
            raise DatasourceError("cassandra not connected", "call connect() first")
        start = time.perf_counter()
        try:
            return self._session.execute(stmt, params)
        except DatasourceError:
            raise
        except Exception as e:  # noqa: BLE001
            raise DatasourceError(e, f"cassandra query failed: {stmt[:120]}") from e
        finally:
            self._observe(stmt, start)

    def exec(self, stmt: str, *params: Any) -> None:
        self._execute(stmt, params)

    def query(self, target: Any, stmt: str, *params: Any) -> Any:
        """Rows bound into ``target``: dict → list[dict]; a dataclass type →
        list of instances (reference reflection-binding parity)."""
        rows = self._execute(stmt, params)
        out = [self._bind_row(r, target) for r in rows]
        return out

    def query_one(self, target: Any, stmt: str, *params: Any) -> Any:
        rows = self.query(target, stmt, *params)
        return rows[0] if rows else None

    def exec_cas(self, stmt: str, *params: Any) -> bool:
        """Lightweight transaction (IF ...): True when applied."""
        rows = self._execute(stmt, params)
        try:
            first = next(iter(rows))
        except StopIteration:
            return True
        if isinstance(first, dict):
            return bool(first.get("[applied]", True))
        return bool(getattr(first, "applied", True))

    @staticmethod
    def _bind_row(row: Any, target: Any):
        as_dict = dict(row) if isinstance(row, dict) else (
            row._asdict() if hasattr(row, "_asdict") else dict(vars(row))
        )
        if target is dict:
            return as_dict
        if dataclasses.is_dataclass(target):
            names = {f.name for f in dataclasses.fields(target)}
            return target(**{k: v for k, v in as_dict.items() if k in names})
        raise DatasourceError(f"unsupported bind target {target!r}", "use dict or a dataclass")

    def health_check(self) -> dict[str, Any]:
        if self._session is None:
            return {"status": "DOWN", "details": {"error": "not connected"}}
        try:
            self._execute("SELECT release_version FROM system.local")
            return {"status": "UP", "details": {"keyspace": self._keyspace}}
        except Exception as e:  # noqa: BLE001
            return {"status": "DOWN", "details": {"error": str(e)}}


# -- in-tree fake --------------------------------------------------------------


class InMemorySession:
    """CRUD-shaped CQL fake for hermetic tests: supports
    CREATE TABLE / INSERT INTO ... VALUES / SELECT [cols|*] FROM ... [WHERE k=?]
    / DELETE FROM ... WHERE / SELECT release_version FROM system.local."""

    def __init__(self, *_a, **_kw):
        self._tables: dict[str, list[dict]] = {}
        self._columns: dict[str, list[str]] = {}

    def execute(self, stmt: str, params: tuple = ()):  # noqa: C901
        s = stmt.strip().rstrip(";")
        low = s.lower()
        if low.startswith("select release_version from system.local"):
            return [{"release_version": "in-memory"}]
        m = re.match(r"create table (?:if not exists )?(\w+)\s*\((.*)\)", low, re.S)
        if m:
            cols = [c.strip().split()[0] for c in m.group(2).split(",") if c.strip()]
            self._tables.setdefault(m.group(1), [])
            self._columns[m.group(1)] = [c for c in cols if c != "primary"]
            return []
        m = re.match(r"insert into (\w+)\s*\(([^)]*)\)\s*values\s*\(([^)]*)\)(\s+if not exists)?", low)
        if m:
            table, cols = m.group(1), [c.strip() for c in m.group(2).split(",")]
            row = dict(zip(cols, params))
            rows = self._tables.setdefault(table, [])
            if m.group(4):  # IF NOT EXISTS on first column as key
                key = cols[0]
                if any(r.get(key) == row.get(key) for r in rows):
                    return [{"[applied]": False}]
                rows.append(row)
                return [{"[applied]": True}]
            rows.append(row)
            return []
        m = re.match(r"select (.*) from (\w+)(?:\s+where\s+(\w+)\s*=\s*\?)?(?:\s+allow filtering)?$", low)
        if m:
            cols_s, table, where = m.groups()
            rows = self._tables.get(table, [])
            if where:
                rows = [r for r in rows if r.get(where) == params[0]]
            if cols_s.strip() == "*":
                return [dict(r) for r in rows]
            want = [c.strip() for c in cols_s.split(",")]
            return [{c: r.get(c) for c in want} for r in rows]
        m = re.match(r"delete from (\w+)\s+where\s+(\w+)\s*=\s*\?", low)
        if m:
            table, col = m.groups()
            rows = self._tables.get(table, [])
            self._tables[table] = [r for r in rows if r.get(col) != params[0]]
            return []
        raise ValueError(f"InMemorySession cannot parse: {stmt!r}")


def in_memory_cassandra(keyspace: str = "test") -> Cassandra:
    return Cassandra(keyspace=keyspace, session_factory=lambda *_: InMemorySession())
