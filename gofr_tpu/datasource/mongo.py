"""Mongo datasource plugin (gofr `pkg/gofr/datasource/mongo/`, separate-
module tier — SURVEY.md §2.4).

Injected by the user via ``app.add_mongo(Mongo(...))``; the container runs
the ``use_logger/use_metrics/connect`` provider lifecycle
(`external_db.go:8-52` pattern). The underlying client class is injectable
(`client_factory``) so the driver is testable without a server — the same
interface-indirection move the reference makes for cassandra
(`cassandra.go:22-26`); ``InMemoryMongo`` is an in-tree fake implementing
the collection surface.

Every operation logs at debug with µs duration and records the
``app_mongo_stats`` histogram (reference: per-driver `app_*_stats`).
"""

from __future__ import annotations

import time
from typing import Any, Callable

from gofr_tpu.datasource import DatasourceError


class Mongo:
    """Narrow consumer interface (container/datasources.go:119-171 parity):
    insert_one/insert_many/find/find_one/update_by_id/update_one/update_many/
    count_documents/delete_one/delete_many/drop + health_check."""

    def __init__(
        self,
        uri: str | None = None,
        database: str = "test",
        client_factory: Callable[..., Any] | None = None,
    ):
        self._uri = uri
        self._db_name = database
        self._client_factory = client_factory
        self._client = None
        self._db = None
        self.logger = None
        self.metrics = None

    # -- provider lifecycle ----------------------------------------------------

    def use_logger(self, logger) -> None:
        self.logger = logger

    def use_metrics(self, metrics) -> None:
        self.metrics = metrics
        try:
            metrics.new_histogram(
                "app_mongo_stats", "mongo operation duration (µs)",
                buckets=[50, 200, 1000, 5000, 20000, 100000, 500000],
            )
        except Exception:  # noqa: BLE001 - already registered
            pass

    def connect(self) -> None:
        factory = self._client_factory
        if factory is None:
            try:
                from pymongo import MongoClient as factory  # type: ignore[import-not-found]
            except ImportError as e:
                raise DatasourceError(e, "pymongo not installed; pass client_factory") from e
        self._client = factory(self._uri) if self._uri else factory()
        self._db = self._client[self._db_name]
        if self.logger:
            self.logger.info(f"connected to mongo database {self._db_name!r}")

    # -- operations ------------------------------------------------------------

    def _observe(self, op: str, collection: str, start: float) -> None:
        micros = (time.perf_counter() - start) * 1e6
        if self.metrics:
            self.metrics.record_histogram("app_mongo_stats", micros, operation=op)
        if self.logger:
            self.logger.debug({"type": "mongo", "operation": op,
                               "collection": collection, "duration_us": round(micros, 1)})

    def _run(self, op: str, collection: str, fn: Callable[[Any], Any]) -> Any:
        if self._db is None:
            raise DatasourceError("mongo not connected", "call connect() first")
        start = time.perf_counter()
        try:
            return fn(self._db[collection])
        except DatasourceError:
            raise
        except Exception as e:  # noqa: BLE001
            raise DatasourceError(e, f"mongo {op} on {collection!r} failed") from e
        finally:
            self._observe(op, collection, start)

    def insert_one(self, collection: str, document: dict) -> Any:
        return self._run("insertOne", collection, lambda c: c.insert_one(document))

    def insert_many(self, collection: str, documents: list[dict]) -> Any:
        return self._run("insertMany", collection, lambda c: c.insert_many(documents))

    def find(self, collection: str, filter: dict | None = None, **kw) -> list[dict]:
        return self._run("find", collection, lambda c: list(c.find(filter or {}, **kw)))

    def find_one(self, collection: str, filter: dict | None = None, **kw) -> dict | None:
        return self._run("findOne", collection, lambda c: c.find_one(filter or {}, **kw))

    def update_one(self, collection: str, filter: dict, update: dict) -> Any:
        return self._run("updateOne", collection, lambda c: c.update_one(filter, update))

    def update_many(self, collection: str, filter: dict, update: dict) -> Any:
        return self._run("updateMany", collection, lambda c: c.update_many(filter, update))

    def update_by_id(self, collection: str, id: Any, update: dict) -> Any:
        return self._run("updateByID", collection,
                         lambda c: c.update_one({"_id": id}, {"$set": update}))

    def count_documents(self, collection: str, filter: dict | None = None) -> int:
        return self._run("countDocuments", collection, lambda c: c.count_documents(filter or {}))

    def delete_one(self, collection: str, filter: dict) -> int:
        return self._run("deleteOne", collection, lambda c: c.delete_one(filter).deleted_count)

    def delete_many(self, collection: str, filter: dict) -> int:
        return self._run("deleteMany", collection, lambda c: c.delete_many(filter).deleted_count)

    def drop(self, collection: str) -> None:
        self._run("drop", collection, lambda c: c.drop())

    def health_check(self) -> dict[str, Any]:
        if self._client is None:
            return {"status": "DOWN", "details": {"error": "not connected"}}
        try:
            ping = getattr(self._client, "admin", None)
            if ping is not None and hasattr(ping, "command"):
                ping.command("ping")
            return {"status": "UP", "details": {"database": self._db_name}}
        except Exception as e:  # noqa: BLE001
            return {"status": "DOWN", "details": {"error": str(e)}}


# -- in-tree fake (hermetic tests / dev; MockContainer tier of SURVEY.md §4) ---


class _Result:
    def __init__(self, **kw):
        self.__dict__.update(kw)


class _InMemoryCollection:
    def __init__(self):
        self._docs: list[dict] = []
        self._next_id = 0

    def insert_one(self, doc: dict):
        doc = dict(doc)
        if "_id" not in doc:
            self._next_id += 1
            doc["_id"] = self._next_id
        self._docs.append(doc)
        return _Result(inserted_id=doc["_id"])

    def insert_many(self, docs: list[dict]):
        return _Result(inserted_ids=[self.insert_one(d).inserted_id for d in docs])

    def _match(self, doc: dict, filt: dict) -> bool:
        return all(doc.get(k) == v for k, v in filt.items())

    def find(self, filt: dict | None = None, **_kw):
        return [dict(d) for d in self._docs if self._match(d, filt or {})]

    def find_one(self, filt: dict | None = None, **_kw):
        hits = self.find(filt)
        return hits[0] if hits else None

    def _apply(self, doc: dict, update: dict) -> None:
        for k, v in update.get("$set", {}).items():
            doc[k] = v
        for k, v in update.get("$inc", {}).items():
            doc[k] = doc.get(k, 0) + v

    def update_one(self, filt: dict, update: dict):
        for d in self._docs:
            if self._match(d, filt):
                self._apply(d, update)
                return _Result(matched_count=1, modified_count=1)
        return _Result(matched_count=0, modified_count=0)

    def update_many(self, filt: dict, update: dict):
        n = 0
        for d in self._docs:
            if self._match(d, filt):
                self._apply(d, update)
                n += 1
        return _Result(matched_count=n, modified_count=n)

    def count_documents(self, filt: dict | None = None) -> int:
        return len(self.find(filt))

    def delete_one(self, filt: dict):
        for i, d in enumerate(self._docs):
            if self._match(d, filt):
                del self._docs[i]
                return _Result(deleted_count=1)
        return _Result(deleted_count=0)

    def delete_many(self, filt: dict):
        before = len(self._docs)
        self._docs = [d for d in self._docs if not self._match(d, filt)]
        return _Result(deleted_count=before - len(self._docs))

    def drop(self):
        self._docs = []


class _InMemoryDatabase:
    def __init__(self):
        self._collections: dict[str, _InMemoryCollection] = {}

    def __getitem__(self, name: str) -> _InMemoryCollection:
        return self._collections.setdefault(name, _InMemoryCollection())


class InMemoryMongoClient:
    """Drop-in ``client_factory`` for hermetic tests: a dict-backed store
    with the collection surface the driver touches."""

    def __init__(self, *_a, **_kw):
        self._dbs: dict[str, _InMemoryDatabase] = {}

    def __getitem__(self, name: str) -> _InMemoryDatabase:
        return self._dbs.setdefault(name, _InMemoryDatabase())


def in_memory_mongo(database: str = "test") -> Mongo:
    """A connected Mongo driver over the in-memory fake."""
    return Mongo(database=database, client_factory=InMemoryMongoClient)
