"""Datasource drivers: each wraps a client + logs + metrics + traces
(gofr `pkg/gofr/datasource/` pattern: observability is free at the driver layer).
"""


class DatasourceError(Exception):
    """Wraps an underlying driver error with a 500 status
    (gofr `datasource/errors.go`)."""

    status_code = 500

    def __init__(self, err: BaseException | str, message: str = ""):
        self.err = err
        self.message = message or str(err)
        super().__init__(self.message)
