"""Fleet metrics federation: compact replica digests, correct merges.

Each replica's ``GossipReporter`` attaches a digest built by ``digest()``
to its periodic snapshot (router/gossip.py) — a JSON-safe dict of the
high-signal counters and latency histograms plus the SLO snapshot and
inflight count. The payload is deliberately small (a handful of families,
raw bucket counts, no exposition text): the same small-payload lesson the
gRPC/TensorFlow microbenchmarks drew for frequent cross-process state
transfer (PAPERS.md, 1804.01138). The router stores the last digest per
replica (``Replica.digest``) and serves two fleet views from it:

- ``fleet_text()`` → Prometheus exposition for the router's ``/metrics``:
  per-replica series carry a ``replica`` label; aggregate series carry no
  replica label. Counters aggregate by summing; histograms aggregate by
  element-wise bucket-count addition ONLY when every replica shares the
  same bucket ladder (otherwise only per-replica series are emitted);
  percentiles are NEVER aggregated — a fleet pXX must be read off the
  merged buckets (``histogram_quantile``), because the average of
  per-replica percentiles is not a percentile of anything.
- ``aggregate_slo()`` → exact fleet attainment/burn per (class, objective,
  window) by summing the good/total counts the SLO snapshot carries —
  again a merge of counts, never an average of ratios.

Everything here is pure data-plumbing over the ``series()`` accessors in
``gofr_tpu.metrics``; no locks, no I/O, trivially testable.
"""

from __future__ import annotations

import math
from typing import Any, Iterable, Mapping

from gofr_tpu.metrics import (
    Counter,
    Gauge,
    Histogram,
    LabelSet,
    Registry,
    _fmt_labels,
    _fmt_value,
)

__all__ = [
    "DIGEST_COUNTERS",
    "DIGEST_GAUGES",
    "DIGEST_HISTOGRAMS",
    "aggregate_perf",
    "aggregate_slo",
    "digest",
    "fleet_text",
    "histogram_quantile",
]

# the high-signal families worth shipping every gossip interval; anything
# else stays scrape-only on the replica's own /metrics port
DIGEST_COUNTERS: tuple[str, ...] = (
    "app_tpu_tokens_total",
    "app_qos_shed_total",
    "app_qos_rejected_total",
    "app_tpu_engine_restarts",
    # quality plane (metrics/quality.py): raw per-(kv_dtype,backend,adapter)
    # sample counts — counters so the fleet rollup is sum(good)/sum(total)
    # exactly, never an average of per-replica agreement ratios
    "app_tpu_quality_samples_total",
    "app_tpu_quality_good_total",
    # KV handoff transfer plane (tpu/handoff.py): raw byte counters so the
    # fleet overlap ratio is sum(overlap)/sum(bytes) exactly — same
    # sum-of-parts discipline as the quality rollup above
    "app_tpu_kv_handoff_bytes_total",
    "app_tpu_kv_handoff_overlap_bytes_total",
)
DIGEST_HISTOGRAMS: tuple[str, ...] = (
    "app_tpu_ttft_seconds",
    "app_tpu_tpot_seconds",
    "app_tpu_e2e_seconds",
    "app_tpu_queue_wait_seconds",
)
DIGEST_GAUGES: tuple[str, ...] = (
    "app_tpu_inflight_requests",
)


def _ls_to_json(ls: LabelSet) -> list[list[str]]:
    return [[k, v] for k, v in ls]


def _ls_from_json(pairs: Iterable[Iterable[str]]) -> LabelSet:
    return tuple(sorted((str(k), str(v)) for k, v in pairs))


def digest(registry: Registry, *, slo=None, inflight: int | None = None,
           perf: Mapping[str, Any] | None = None,
           knobs: Mapping[str, Any] | None = None,
           counters: Iterable[str] = DIGEST_COUNTERS,
           histograms: Iterable[str] = DIGEST_HISTOGRAMS,
           gauges: Iterable[str] = DIGEST_GAUGES) -> dict[str, Any]:
    """Compact, JSON-safe snapshot of one replica's federated state."""
    out: dict[str, Any] = {"v": 1, "counters": {}, "hists": {}, "gauges": {}}
    for name in counters:
        m = registry.get(name)
        if isinstance(m, Counter):
            series = m.series()
            if series:
                out["counters"][name] = [
                    [_ls_to_json(ls), v] for ls, v in series]
    for name in histograms:
        m = registry.get(name)
        if isinstance(m, Histogram):
            series = m.series()
            if series:
                out["hists"][name] = {
                    "buckets": list(m.buckets),
                    "series": [[_ls_to_json(ls), counts, s, total]
                               for ls, counts, s, total in series],
                }
    for name in gauges:
        m = registry.get(name)
        if isinstance(m, Gauge):
            series = m.series()
            if series:
                out["gauges"][name] = [[_ls_to_json(ls), v] for ls, v in series]
    if slo is not None:
        out["slo"] = slo.snapshot()
    if inflight is not None:
        out["inflight"] = int(inflight)
    if perf is not None:
        # the perf-plane window totals (metrics/perf.py merge_totals
        # payload): exact numerator/denominator sums, so the router can
        # merge replicas the same way it merges SLO counts
        out["perf"] = dict(perf)
    if knobs is not None:
        # per-engine live tuning-knob vectors (engine.knob_vector, with the
        # online controller's _controlled marker): /debug/fleet shows WHO
        # runs which tuning, so a replica whose controller drifted from the
        # fleet's pins is visible from the router
        out["knobs"] = dict(knobs)
    return out


def histogram_quantile(buckets: Iterable[float], counts: Iterable[int],
                       total: int, q: float) -> float | None:
    """Estimate the q-quantile (q in [0, 1]) from NON-cumulative bucket
    counts, returning the upper bound of the bucket the rank lands in —
    the only legal way to get a fleet pXX (merge counts first, then read
    the quantile; averaging per-replica percentiles is statistically
    meaningless). Returns None with no samples, and +inf when the rank
    falls in the overflow tail above the last finite bucket."""
    total = int(total)
    if total <= 0:
        return None
    rank = q * total
    cum = 0
    for b, c in zip(buckets, counts):
        cum += c
        if cum >= rank:
            return float(b)
    return math.inf


# -- merge + exposition --------------------------------------------------------


def _merge_counters(name: str, digests: Mapping[str, Mapping[str, Any]]):
    """-> (aggregate {ls: value}, per-replica [(replica, ls, value)])."""
    agg: dict[LabelSet, float] = {}
    per: list[tuple[str, LabelSet, float]] = []
    for replica in sorted(digests):
        for pairs, v in digests[replica].get("counters", {}).get(name, []):
            ls = _ls_from_json(pairs)
            agg[ls] = agg.get(ls, 0.0) + float(v)
            per.append((replica, ls, float(v)))
    return agg, per


def _merge_hists(name: str, digests: Mapping[str, Mapping[str, Any]]):
    """-> (shared buckets | None, aggregate {ls: [counts, sum, total]},
    per-replica [(replica, ls, buckets, counts, sum, total)]). The
    aggregate is None-keyed out (empty) when replicas disagree on the
    bucket ladder — summing mismatched buckets would silently corrupt
    every derived quantile, so we refuse and keep per-replica series."""
    ladders = set()
    per: list[tuple[str, LabelSet, tuple, list[int], float, int]] = []
    for replica in sorted(digests):
        h = digests[replica].get("hists", {}).get(name)
        if not h:
            continue
        buckets = tuple(float(b) for b in h.get("buckets", ()))
        ladders.add(buckets)
        for pairs, counts, s, total in h.get("series", []):
            per.append((replica, _ls_from_json(pairs), buckets,
                        [int(c) for c in counts], float(s), int(total)))
    shared = next(iter(ladders)) if len(ladders) == 1 else None
    agg: dict[LabelSet, list] = {}
    if shared is not None:
        for _, ls, _, counts, s, total in per:
            cur = agg.get(ls)
            if cur is None:
                agg[ls] = [list(counts), s, total]
            else:
                for i, c in enumerate(counts):
                    cur[0][i] += c
                cur[1] += s
                cur[2] += total
    return shared, agg, per


def _with_replica(ls: LabelSet, replica: str) -> LabelSet:
    return tuple(sorted(ls + (("replica", replica),)))


def _hist_lines(name: str, ls: LabelSet, buckets, counts, total_sum, total,
                lines: list[str]) -> None:
    cum = 0
    for b, c in zip(buckets, counts):
        cum += c
        le = 'le="' + _fmt_value(b) + '"'
        lines.append(f"{name}_bucket{_fmt_labels(ls, le)} {cum}")
    inf = 'le="+Inf"'
    lines.append(f"{name}_bucket{_fmt_labels(ls, inf)} {total}")
    lines.append(f"{name}_sum{_fmt_labels(ls)} {_fmt_value(total_sum)}")
    lines.append(f"{name}_count{_fmt_labels(ls)} {total}")


def fleet_text(digests: Mapping[str, Mapping[str, Any]],
               states: Mapping[str, Mapping[str, Any]] | None = None) -> str:
    """Prometheus exposition for the router's fleet ``/metrics``: aggregate
    series (no replica label) + per-replica series (``replica=...``), plus
    registry-state gauges and the per-replica SLO attainment/burn gauges
    derived from the digests' SLO snapshots."""
    lines: list[str] = []

    names = sorted({n for d in digests.values()
                    for n in d.get("counters", {})})
    for name in names:
        agg, per = _merge_counters(name, digests)
        lines.append(f"# TYPE {name} counter")
        for ls in sorted(agg):
            lines.append(f"{name}{_fmt_labels(ls)} {_fmt_value(agg[ls])}")
        for replica, ls, v in per:
            lines.append(
                f"{name}{_fmt_labels(_with_replica(ls, replica))} {_fmt_value(v)}")

    names = sorted({n for d in digests.values() for n in d.get("hists", {})})
    for name in names:
        shared, agg, per = _merge_hists(name, digests)
        lines.append(f"# TYPE {name} histogram")
        if shared is not None:
            for ls in sorted(agg):
                counts, s, total = agg[ls]
                _hist_lines(name, ls, shared, counts, s, total, lines)
        for replica, ls, buckets, counts, s, total in per:
            _hist_lines(name, _with_replica(ls, replica), buckets, counts,
                        s, total, lines)

    names = sorted({n for d in digests.values() for n in d.get("gauges", {})})
    for name in names:
        lines.append(f"# TYPE {name} gauge")
        for replica in sorted(digests):
            for pairs, v in digests[replica].get("gauges", {}).get(name, []):
                ls = _with_replica(_ls_from_json(pairs), replica)
                lines.append(f"{name}{_fmt_labels(ls)} {_fmt_value(v)}")

    _slo_lines(digests, lines)
    _perf_lines(digests, lines)
    _handoff_lines(digests, lines)
    _state_lines(digests, states or {}, lines)
    return "\n".join(lines) + "\n"


def _handoff_lines(digests: Mapping[str, Mapping[str, Any]],
                   lines: list[str]) -> None:
    """Fleet KV-handoff overlap ratio, derived from the digests' byte
    counters (export side): sum(overlap bytes)/sum(total bytes) across
    every prefill replica — the streaming pipeline's fleet-wide "how much
    transfer hid behind prefill compute", never an average of per-replica
    ratios."""
    bytes_agg, _ = _merge_counters("app_tpu_kv_handoff_bytes_total", digests)
    total = sum(v for ls, v in bytes_agg.items()
                if ("side", "export") in ls)
    if total <= 0:
        return
    over_agg, _ = _merge_counters(
        "app_tpu_kv_handoff_overlap_bytes_total", digests)
    overlap = sum(v for ls, v in over_agg.items()
                  if ("side", "export") in ls)
    lines.append("# TYPE app_tpu_kv_handoff_overlap_ratio gauge")
    lines.append(
        f"app_tpu_kv_handoff_overlap_ratio {_fmt_value(overlap / total)}")


def _slo_lines(digests: Mapping[str, Mapping[str, Any]],
               lines: list[str]) -> None:
    """Per-replica + exact-merged aggregate SLO gauges. Attainment merges
    as sum(good)/sum(total) — counts, never an average of ratios."""
    have = any(d.get("slo") for d in digests.values())
    if not have:
        return
    fleet = aggregate_slo(digests)
    lines.append("# TYPE app_slo_attainment gauge")
    att: list[str] = []
    burn: list[str] = []
    for cname in sorted(fleet):
        for oname in sorted(fleet[cname]):
            entry = fleet[cname][oname]
            for w in ("fast", "slow"):
                win = entry[w]
                ls: LabelSet = tuple(sorted(
                    (("class", cname), ("objective", oname), ("window", w))))
                if win["attainment"] is not None:
                    att.append(
                        f"app_slo_attainment{_fmt_labels(ls)} "
                        f"{_fmt_value(win['attainment'])}")
                if win["burn_rate"] is not None:
                    burn.append(
                        f"app_slo_burn_rate{_fmt_labels(ls)} "
                        f"{_fmt_value(win['burn_rate'])}")
    for replica in sorted(digests):
        snap = digests[replica].get("slo") or {}
        for cname in sorted(snap):
            for oname in sorted(snap[cname]):
                entry = snap[cname][oname]
                for w in ("fast", "slow"):
                    win = entry.get(w) or {}
                    ls = tuple(sorted((("class", cname), ("objective", oname),
                                       ("window", w), ("replica", replica))))
                    if win.get("attainment") is not None:
                        att.append(
                            f"app_slo_attainment{_fmt_labels(ls)} "
                            f"{_fmt_value(win['attainment'])}")
                    if win.get("burn_rate") is not None:
                        burn.append(
                            f"app_slo_burn_rate{_fmt_labels(ls)} "
                            f"{_fmt_value(win['burn_rate'])}")
    lines.extend(att)
    lines.append("# TYPE app_slo_burn_rate gauge")
    lines.extend(burn)


def _perf_lines(digests: Mapping[str, Mapping[str, Any]],
                lines: list[str]) -> None:
    """Fleet roofline gauges from the perf digests: like SLO attainment,
    the aggregate MFU/MBU is recomputed from summed FLOPs/bytes over
    summed capacity (device_s x peak) — never an average of per-replica
    ratios, which would weight an idle replica the same as a saturated
    one."""
    from gofr_tpu.metrics import perf as perf_mod

    have = any(d.get("perf") for d in digests.values())
    if not have:
        return
    fleet = aggregate_perf(digests)
    derived = perf_mod.derive(fleet)
    for gname, util in (("app_tpu_mfu", derived["mfu"]),
                        ("app_tpu_mbu", derived["mbu"])):
        lines.append(f"# TYPE {gname} gauge")
        for key in sorted(util):
            kind, _, dtype = key.partition("|")
            ls: LabelSet = tuple(sorted(
                (("kind", kind), ("kv_dtype", dtype))))
            lines.append(f"{gname}{_fmt_labels(ls)} {_fmt_value(util[key])}")
        for replica in sorted(digests):
            part = digests[replica].get("perf")
            if not part:
                continue
            rd = perf_mod.derive(part)["mfu" if gname.endswith("mfu") else "mbu"]
            for key in sorted(rd):
                kind, _, dtype = key.partition("|")
                ls = tuple(sorted((("kind", kind), ("kv_dtype", dtype),
                                   ("replica", replica))))
                lines.append(
                    f"{gname}{_fmt_labels(ls)} {_fmt_value(rd[key])}")
    # per-adapter attribution (multi-LoRA multiplexing): fleet rows are
    # exact sum-of-parts like the kind rows above; device_seconds is the
    # per-tenant COGS meter (docs/serving.md)
    ad_fleet = derived.get("adapters") or {}
    ad_parts = {replica: (perf_mod.derive(digests[replica]["perf"])
                          .get("adapters") or {})
                for replica in sorted(digests)
                if digests[replica].get("perf")}
    if ad_fleet or any(ad_parts.values()):
        for gname, field in (("app_tpu_adapter_mfu", "mfu"),
                             ("app_tpu_adapter_mbu", "mbu"),
                             ("app_tpu_adapter_device_seconds", "device_s")):
            lines.append(f"# TYPE {gname} gauge")
            for aid in sorted(ad_fleet):
                val = ad_fleet[aid].get(field)
                if val is None:
                    continue
                ls = (("adapter", aid),)
                lines.append(f"{gname}{_fmt_labels(ls)} {_fmt_value(val)}")
            for replica, rows in ad_parts.items():
                for aid in sorted(rows):
                    val = rows[aid].get(field)
                    if val is None:
                        continue
                    ls = tuple(sorted(
                        (("adapter", aid), ("replica", replica))))
                    lines.append(
                        f"{gname}{_fmt_labels(ls)} {_fmt_value(val)}")
    lines.append("# TYPE app_tpu_pipeline_bubble_ratio gauge")
    ratio = derived["bubble_ratio"]
    if ratio is not None:
        lines.append(f"app_tpu_pipeline_bubble_ratio {_fmt_value(ratio)}")
    for replica in sorted(digests):
        part = digests[replica].get("perf")
        if not part:
            continue
        r = perf_mod.derive(part)["bubble_ratio"]
        if r is not None:
            ls = (("replica", replica),)
            lines.append(
                f"app_tpu_pipeline_bubble_ratio{_fmt_labels(ls)} "
                f"{_fmt_value(r)}")


def aggregate_perf(digests: Mapping[str, Mapping[str, Any]]) -> dict[str, Any]:
    """Exact fleet perf roll-up: merge every replica's perf-window totals
    (metrics/perf.py payload) by summing FLOPs/bytes numerators and
    capacity denominators per (kind, kv_dtype). Feed the result to
    ``perf.derive`` for fleet MFU/MBU/bubble ratios."""
    from gofr_tpu.metrics import perf as perf_mod

    return perf_mod.merge_totals(
        d.get("perf") for d in digests.values() if d.get("perf"))


def _state_lines(digests: Mapping[str, Mapping[str, Any]],
                 states: Mapping[str, Mapping[str, Any]],
                 lines: list[str]) -> None:
    if not states and not any("inflight" in d for d in digests.values()):
        return
    if states:
        lines.append("# TYPE app_fleet_replica_up gauge")
        for replica in sorted(states):
            st = states[replica]
            up = 1 if str(st.get("status", "")).upper() == "UP" else 0
            ls: LabelSet = (("replica", replica),)
            role = str(st.get("role", "") or "")
            if role and role != "both":
                # role label only for a role-split member (disaggregated
                # serving): colocated fleets keep the exact pre-role series
                ls = (("replica", replica), ("role", role))
            lines.append(f"app_fleet_replica_up{_fmt_labels(ls)} {up}")
        lines.append("# TYPE app_fleet_replica_epoch gauge")
        for replica in sorted(states):
            ls = (("replica", replica),)
            lines.append(
                f"app_fleet_replica_epoch{_fmt_labels(ls)} "
                f"{int(states[replica].get('epoch', 0) or 0)}")
    inflight = {r: d["inflight"] for r, d in digests.items()
                if isinstance(d.get("inflight"), int)}
    if inflight:
        lines.append("# TYPE app_fleet_replica_inflight gauge")
        for replica in sorted(inflight):
            ls = (("replica", replica),)
            lines.append(
                f"app_fleet_replica_inflight{_fmt_labels(ls)} "
                f"{inflight[replica]}")


def aggregate_slo(digests: Mapping[str, Mapping[str, Any]]) -> dict[str, Any]:
    """Exact fleet SLO roll-up: per (class, objective, window), sum the
    good/total counts from every replica's snapshot and recompute
    attainment/burn from the sums. Target is taken as the max across
    replicas (the conservative bound if configs momentarily disagree)."""
    acc: dict[tuple[str, str], dict[str, Any]] = {}
    for d in digests.values():
        snap = d.get("slo") or {}
        for cname, objs in snap.items():
            for oname, entry in objs.items():
                key = (cname, oname)
                cur = acc.setdefault(key, {
                    "target": 0.0,
                    "fast": {"good": 0, "total": 0},
                    "slow": {"good": 0, "total": 0},
                })
                cur["target"] = max(cur["target"], float(entry.get("target", 0.0)))
                for w in ("fast", "slow"):
                    win = entry.get(w) or {}
                    cur[w]["good"] += int(win.get("good", 0) or 0)
                    cur[w]["total"] += int(win.get("total", 0) or 0)
    out: dict[str, Any] = {}
    for (cname, oname), cur in acc.items():
        entry: dict[str, Any] = {"target": cur["target"]}
        budget = 1.0 - cur["target"]
        for w in ("fast", "slow"):
            good, total = cur[w]["good"], cur[w]["total"]
            att = good / total if total else None
            burn = ((1.0 - att) / budget
                    if att is not None and budget > 0 else None)
            entry[w] = {
                "good": good, "total": total,
                "attainment": round(att, 6) if att is not None else None,
                "burn_rate": round(burn, 4) if burn is not None else None,
            }
        slow_burn = entry["slow"]["burn_rate"]
        entry["budget_remaining"] = (
            round(max(0.0, min(1.0, 1.0 - slow_burn)), 4)
            if slow_burn is not None else None)
        out.setdefault(cname, {})[oname] = entry
    return out
