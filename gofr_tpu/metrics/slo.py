"""SLO engine: per-QoS-class objectives, rolling attainment, error budgets.

PR 2 gave every process raw latency histograms (``app_tpu_{ttft,tpot,e2e}
_seconds``); this module turns those same samples into the signal operators
actually page on — *is each class meeting its objective, and how fast is it
burning error budget* (Google-SRE multi-window burn-rate alerting).

Objectives are declarative, per QoS class, config-driven with sane defaults
(``SLO_<CLASS>_TTFT_MS`` / ``_TPOT_MS`` / ``_E2E_MS`` / ``_AVAILABILITY``;
docs/observability.md has the full table). Each (class, objective) pair keeps
two bucketed ring windows — fast (~1m) and slow (~1h), fixed memory, no
per-sample retention — and derives:

- **attainment**: fraction of samples meeting the objective in the window,
  exported as ``app_slo_attainment{class,objective,window}``;
- **burn rate**: ``(1 - attainment) / (1 - target)`` — 1.0 means the error
  budget is being consumed exactly at the sustainable pace, N means N× too
  fast (``app_slo_burn_rate{...}``);
- **budget remaining**: ``1 - burn`` over the slow window, clamped to
  [0, 1] (``app_slo_budget_remaining{class,objective}``).

A sustained fast-window burn above ``SLO_BURN_THRESHOLD`` (with at least
``SLO_MIN_SAMPLES`` samples — a single slow request must not page anyone)
flips ``health_check()`` to DEGRADED with the breaching (class, objective,
burn) as a structured reason; the container joins it into ``/.well-known/
health`` and the gossip snapshot carries it to the router tier. QoS's
admission controller may consult ``should_shed`` as a pressure signal
(``QOS_SHED_ON_BURN``: shed lower classes while a higher class burns).

``CaptureWatcher`` is the trigger-fired anomaly capture (off unless
``SLO_CAPTURE=true``): on a burn-rate breach it snapshots the flight
recorder rings + engine health to a timestamped bundle under the profiler
directory — token-bucket rate-limited (``SLO_CAPTURE_MIN_INTERVAL_S``,
``SLO_CAPTURE_BURST``) so a sustained breach costs one artifact, not a full
disk — and can optionally wrap a bounded ``jax.profiler.trace`` around the
next few device steps (``SLO_CAPTURE_TRACE_S``).

Feed points: the engine device loop / completion path (tpu/engine.py
``_mark_first_token`` → ttft, ``_maybe_finish`` → tpot, ``_observe_done`` →
e2e + availability) — the exact callsites that record the raw histograms,
so the two views can never disagree about what was measured.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable

__all__ = ["CaptureWatcher", "Objective", "SLOEngine", "SLOTracker"]

LATENCY_OBJECTIVES = ("ttft", "tpot", "e2e")

# sane defaults (ms): overridable per class via SLO_<CLASS>_<OBJ>_MS; a
# class outside this table inherits the "default" row. 0/negative disables
# that (class, objective) pair.
_DEFAULT_THRESHOLDS_MS: dict[str, dict[str, float]] = {
    "interactive": {"ttft": 2000.0, "tpot": 100.0, "e2e": 30000.0},
    "default": {"ttft": 5000.0, "tpot": 250.0, "e2e": 60000.0},
    "batch": {"ttft": 30000.0, "tpot": 1000.0, "e2e": 300000.0},
}
_DEFAULT_AVAILABILITY = {"interactive": 0.999, "default": 0.99, "batch": 0.95}


@dataclass(frozen=True)
class Objective:
    """One declarative (class, objective) target. ``threshold_s`` is the
    latency bound a sample must meet (None for availability, where the
    sample itself is already good/bad); ``target`` is the attainment
    fraction the error budget is sized against (0.99 → 1% budget)."""

    cls: str
    name: str                   # ttft | tpot | e2e | availability
    target: float
    threshold_s: float | None = None


class _WindowRing:
    """Bucketed time ring covering ``window_s``: O(buckets) memory forever,
    regardless of traffic. Each bucket stores (good, total) for one
    ``window_s / buckets`` slice; a write to a recycled slot resets it, so
    reads just skip slots whose last-write epoch fell out of the window.
    The newest partial bucket is included, so a window can briefly see up
    to one bucket-width of extra history — irrelevant at 60 buckets."""

    __slots__ = ("width", "n", "_good", "_total", "_epoch")

    def __init__(self, window_s: float, buckets: int = 60):
        self.n = max(1, int(buckets))
        self.width = float(window_s) / self.n
        self._good = [0] * self.n
        self._total = [0] * self.n
        self._epoch = [-1] * self.n

    def observe(self, ok: bool, now: float) -> None:
        idx = int(now / self.width)
        slot = idx % self.n
        if self._epoch[slot] != idx:
            self._epoch[slot] = idx
            self._good[slot] = 0
            self._total[slot] = 0
        self._total[slot] += 1
        if ok:
            self._good[slot] += 1

    def stats(self, now: float) -> tuple[int, int]:
        lo = int(now / self.width) - self.n + 1
        good = total = 0
        for slot in range(self.n):
            if self._epoch[slot] >= lo:
                good += self._good[slot]
                total += self._total[slot]
        return good, total


class SLOTracker:
    """Attainment/burn state for one (class, objective): a fast and a slow
    window ring plus the derived SRE arithmetic."""

    __slots__ = ("objective", "fast", "slow")

    def __init__(self, objective: Objective, fast_s: float, slow_s: float,
                 buckets: int = 60):
        self.objective = objective
        self.fast = _WindowRing(fast_s, buckets)
        self.slow = _WindowRing(slow_s, buckets)

    def observe(self, ok: bool, now: float) -> None:
        self.fast.observe(ok, now)
        self.slow.observe(ok, now)

    def burn(self, good: int, total: int) -> float | None:
        """Error-budget burn rate: bad fraction over budget fraction. 1.0 =
        burning exactly at the sustainable pace; None with no samples or a
        degenerate target (budget 0)."""
        budget = 1.0 - self.objective.target
        if total <= 0 or budget <= 0:
            return None
        return (1.0 - good / total) / budget

    def window(self, which: str, now: float) -> dict[str, Any]:
        ring = self.fast if which == "fast" else self.slow
        good, total = ring.stats(now)
        att = good / total if total else None
        burn = self.burn(good, total)
        return {
            "good": good, "total": total,
            "attainment": round(att, 6) if att is not None else None,
            "burn_rate": round(burn, 4) if burn is not None else None,
        }


class SLOEngine:
    """The per-process SLO brain: owns the (class, objective) trackers,
    exports the three ``app_slo_*`` gauge families on every scrape, flips
    health to DEGRADED on sustained fast-window burn, and notifies breach
    listeners (the anomaly CaptureWatcher) at most once per
    ``check_interval_s``. Thread-safe; ``now`` is injectable for tests."""

    def __init__(self, objectives: list[Objective], *, metrics=None,
                 logger=None, fast_window_s: float = 60.0,
                 slow_window_s: float = 3600.0, burn_threshold: float = 10.0,
                 min_samples: int = 10, check_interval_s: float = 1.0,
                 default_class: str = "default",
                 rank: dict[str, int] | None = None,
                 now: Callable[[], float] = time.monotonic):
        self.metrics = metrics
        self.logger = logger
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_threshold = float(burn_threshold)
        self.min_samples = int(min_samples)
        self.check_interval_s = float(check_interval_s)
        self.default_class = default_class
        self._now = now
        self._rank = dict(rank or {})
        self._trackers: dict[tuple[str, str], SLOTracker] = {
            (o.cls, o.name): SLOTracker(o, fast_window_s, slow_window_s)
            for o in objectives
        }
        self._classes = {o.cls for o in objectives}
        if default_class not in self._classes and self._trackers:
            # an explicit vocabulary without "default": unlabeled samples
            # land in the lowest-priority class rather than vanishing
            self.default_class = min(
                self._classes, key=lambda c: -self._rank.get(c, 0))
        self._listeners: list[Callable[[list[dict]], Any]] = []
        self._last_check = 0.0
        self._lock = threading.Lock()

    # -- construction ----------------------------------------------------------

    @classmethod
    def from_config(cls, config, *, metrics=None, logger=None,
                    now: Callable[[], float] = time.monotonic) -> "SLOEngine":
        """Build from ``SLO_*`` config. The class vocabulary (and the
        priority rank ``should_shed`` uses) comes from the same ``QOS_*``
        keys the admission controller and router read, so all three tiers
        agree on what a class name means."""
        from gofr_tpu.qos import QoSPolicy

        qpol = QoSPolicy.from_config(config)
        names = [c.name for c in qpol.classes]
        rank = {name: i for i, name in enumerate(names)}
        base_target = config.get_float("SLO_TARGET", 0.99)
        objectives: list[Objective] = []
        for name in names:
            up = name.upper()
            defaults = _DEFAULT_THRESHOLDS_MS.get(
                name, _DEFAULT_THRESHOLDS_MS["default"])
            target = config.get_float(f"SLO_{up}_TARGET", base_target)
            for obj in LATENCY_OBJECTIVES:
                ms = config.get_float(f"SLO_{up}_{obj.upper()}_MS",
                                      defaults[obj])
                if ms > 0:
                    objectives.append(Objective(name, obj, target, ms / 1000.0))
            avail = config.get_float(
                f"SLO_{up}_AVAILABILITY",
                _DEFAULT_AVAILABILITY.get(name, _DEFAULT_AVAILABILITY["default"]))
            if 0.0 < avail < 1.0:
                objectives.append(Objective(name, "availability", avail))
            # quality objective (metrics/quality.py shadow scorer): target =
            # fraction of shadow-scored samples that must sit within the
            # divergence thresholds. Default 0 = off — it only costs budget
            # when the operator both samples traffic (QUALITY_SHADOW_RATE)
            # and declares a target here.
            quality = config.get_float(f"SLO_{up}_QUALITY", 0.0)
            if 0.0 < quality < 1.0:
                objectives.append(Objective(name, "quality", quality))
        return cls(
            objectives, metrics=metrics, logger=logger,
            fast_window_s=config.get_float("SLO_FAST_WINDOW_S", 60.0),
            slow_window_s=config.get_float("SLO_SLOW_WINDOW_S", 3600.0),
            burn_threshold=config.get_float("SLO_BURN_THRESHOLD", 10.0),
            min_samples=config.get_int("SLO_MIN_SAMPLES", 10),
            check_interval_s=config.get_float("SLO_CHECK_INTERVAL_S", 1.0),
            default_class=qpol.default_class, rank=rank, now=now)

    # -- feeds (engine record points) ------------------------------------------

    def _canon(self, cls_name: str | None) -> str:
        """Unknown/absent class labels (QoS off records "none") fold into
        the default class, mirroring ``QoSPolicy.resolve``."""
        if cls_name in self._classes:
            return cls_name  # type: ignore[return-value]
        return self.default_class

    def observe(self, cls_name: str | None, objective: str, seconds: float) -> None:
        """One latency sample against the (class, objective) threshold.
        No-op for disabled objectives — the hot path pays a dict probe."""
        tr = self._trackers.get((self._canon(cls_name), objective))
        if tr is None or tr.objective.threshold_s is None:
            return
        now = self._now()
        with self._lock:
            tr.observe(seconds <= tr.objective.threshold_s, now)
        self._maybe_check(now)

    def observe_outcome(self, cls_name: str | None, ok: bool) -> None:
        """One availability sample: did the request complete without error
        (timeouts, sheds, and engine faults all count against budget)."""
        tr = self._trackers.get((self._canon(cls_name), "availability"))
        if tr is None:
            return
        now = self._now()
        with self._lock:
            tr.observe(bool(ok), now)
        self._maybe_check(now)

    def observe_quality(self, cls_name: str | None, ok: bool) -> None:
        """One shadow-scored quality sample (metrics/quality.py): did the
        request's re-score stay within the divergence thresholds. Rides the
        same window/burn/breach machinery as every other objective, so a
        numerics regression degrades health and fires captures exactly like
        a latency regression would."""
        tr = self._trackers.get((self._canon(cls_name), "quality"))
        if tr is None:
            return
        now = self._now()
        with self._lock:
            tr.observe(bool(ok), now)
        self._maybe_check(now)

    # -- derived views ---------------------------------------------------------

    def snapshot(self, now: float | None = None) -> dict[str, Any]:
        """Nested {class: {objective: windows}} view — the compact digest
        the gossip snapshot ships to the router tier (window good/total
        counts ride along so fleet aggregation can merge them EXACTLY:
        attainment is a ratio of counts, so the fleet number is
        sum(good)/sum(total), never an average of ratios)."""
        t = self._now() if now is None else now
        out: dict[str, Any] = {}
        with self._lock:
            items = list(self._trackers.items())
        for (cname, oname), tr in items:
            with self._lock:
                fast = tr.window("fast", t)
                slow = tr.window("slow", t)
            burn_slow = slow["burn_rate"]
            entry: dict[str, Any] = {
                "target": tr.objective.target,
                "fast": fast, "slow": slow,
                "budget_remaining": (
                    round(max(0.0, min(1.0, 1.0 - burn_slow)), 4)
                    if burn_slow is not None else None),
            }
            if tr.objective.threshold_s is not None:
                entry["threshold_ms"] = tr.objective.threshold_s * 1000.0
            out.setdefault(cname, {})[oname] = entry
        return out

    def breaches(self, now: float | None = None) -> list[dict[str, Any]]:
        """(class, objective) pairs whose FAST-window burn sits at or above
        the threshold with enough samples to mean something — the
        structured reason behind DEGRADED health and the capture trigger."""
        t = self._now() if now is None else now
        out = []
        with self._lock:
            for (cname, oname), tr in self._trackers.items():
                good, total = tr.fast.stats(t)
                if total < self.min_samples:
                    continue
                burn = tr.burn(good, total)
                if burn is not None and burn >= self.burn_threshold:
                    out.append({
                        "class": cname, "objective": oname, "window": "fast",
                        "burn_rate": round(burn, 4),
                        "attainment": round(good / total, 6),
                        "samples": total, "target": tr.objective.target,
                    })
        return out

    def burning_classes(self, now: float | None = None) -> set[str]:
        return {b["class"] for b in self.breaches(now)}

    def pressure(self, now: float | None = None) -> dict[str, Any]:
        """The autoscaler's pressure reading (fleet/autoscaler.py): the
        WORST fast-window burn across every tracked (class, objective)
        pair, regardless of the breach gate's ``burn_threshold`` — the
        scale-out threshold is the autoscale policy's to set. ``burn`` is
        None when no window holds ``min_samples`` yet — an idle fleet,
        which the decider (together with an empty queue) reads as calm so
        a quiet fleet can still scale in; a silent SIGNAL PLANE is the
        reading's ``age_s``, and that is what freezes decisions."""
        t = self._now() if now is None else now
        worst: float | None = None
        source = None
        samples = 0
        with self._lock:
            for (cname, oname), tr in self._trackers.items():
                good, total = tr.fast.stats(t)
                samples += total
                if total < self.min_samples:
                    continue
                burn = tr.burn(good, total)
                if burn is not None and (worst is None or burn > worst):
                    worst, source = burn, f"{cname}/{oname}"
        return {"burn": worst, "source": source, "samples": samples}

    def should_shed(self, cls_name: str | None, now: float | None = None) -> bool:
        """QoS pressure signal (``QOS_SHED_ON_BURN``): shed this class when
        a STRICTLY higher-priority class is burning its fast budget — the
        capacity freed is exactly what the burning class needs, and the
        burning class itself is never shed by its own burn (that would turn
        every breach into an outage)."""
        mine = self._rank.get(self._canon(cls_name), 0)
        return any(self._rank.get(c, mine) < mine
                   for c in self.burning_classes(now))

    def health_check(self) -> dict[str, Any]:
        br = self.breaches()
        if br:
            return {"status": "DEGRADED", "details": {"burning": br}}
        return {"status": "UP", "details": {"burning": []}}

    # -- exposition ------------------------------------------------------------

    def sample_gauges(self, registry=None) -> None:
        """Metrics collect hook: refresh the three ``app_slo_*`` families
        on every scrape. Windows with zero samples publish nothing — an
        idle class must not read as 100% attained (or 0%)."""
        reg = registry if registry is not None else self.metrics
        if reg is None:
            return
        now = self._now()
        snap = self.snapshot(now)
        for cname, objs in snap.items():
            for oname, entry in objs.items():
                labels = {"class": cname, "objective": oname}
                for w in ("fast", "slow"):
                    win = entry[w]
                    if win["attainment"] is None:
                        continue
                    reg.set_gauge("app_slo_attainment", win["attainment"],
                                  window=w, **labels)
                    if win["burn_rate"] is not None:
                        reg.set_gauge("app_slo_burn_rate", win["burn_rate"],
                                      window=w, **labels)
                if entry["budget_remaining"] is not None:
                    reg.set_gauge("app_slo_budget_remaining",
                                  entry["budget_remaining"], **labels)

    # -- breach notification ---------------------------------------------------

    def add_breach_listener(self, fn: Callable[[list[dict]], Any]) -> None:
        """Register a callback invoked (outside the lock, on the observing
        thread) with the current breach list, at most once per
        ``check_interval_s`` while a breach persists."""
        self._listeners.append(fn)

    def _maybe_check(self, now: float) -> None:
        if not self._listeners:
            return
        with self._lock:
            if now - self._last_check < self.check_interval_s:
                return
            self._last_check = now
        br = self.breaches(now)
        if not br:
            return
        for fn in list(self._listeners):
            try:
                fn(br)
            except Exception as e:  # noqa: BLE001 - a listener must not poison the record path
                if self.logger is not None:
                    self.logger.warnf("slo breach listener failed: %r", e)


class CaptureWatcher:
    """Trigger-fired anomaly capture: on a burn-rate breach, snapshot the
    flight recorder rings + engine health (+ the SLO state itself) to a
    timestamped bundle directory — the "TTFT p99 spiked at 3am" artifact.

    Token-bucket rate-limited: ``burst`` captures available up front, one
    refilled every ``min_interval_s`` — a breach that persists for an hour
    costs a handful of bundles, not a full disk. Off unless the app opts in
    (``SLO_CAPTURE=true``); both clocks are injectable for tests."""

    def __init__(self, container, slo: SLOEngine, *, out_dir: str,
                 min_interval_s: float = 600.0, burst: int = 1,
                 trace_s: float = 0.0, flight_requests: int = 64,
                 flight_steps: int = 128, max_bundles: int = 32,
                 now: Callable[[], float] = time.monotonic,
                 clock: Callable[[], float] = time.time):
        self.container = container
        self.slo = slo
        self.out_dir = out_dir
        self.min_interval_s = max(float(min_interval_s), 1e-9)
        self.burst = max(1, int(burst))
        self.trace_s = float(trace_s)
        self.flight_requests = int(flight_requests)
        self.flight_steps = int(flight_steps)
        # disk retention: the token bucket bounds bundles per interval, this
        # bounds them across days — oldest slo-capture-* dirs are swept
        # after each write (0 = unbounded, the pre-retention behavior)
        self.max_bundles = int(max_bundles)
        self._now = now
        self._clock = clock
        self._tokens = float(self.burst)
        self._refill_at = now()
        self._seq = 0
        self._tracing = False
        self._lock = threading.Lock()

    @classmethod
    def from_config(cls, config, container, slo: SLOEngine,
                    **kw: Any) -> "CaptureWatcher":
        out_dir = config.get_or_default(
            "SLO_CAPTURE_DIR",
            config.get_or_default("PROFILER_DIR", "/tmp/gofr_tpu_profile"))
        return cls(
            container, slo, out_dir=out_dir,
            min_interval_s=config.get_float("SLO_CAPTURE_MIN_INTERVAL_S", 600.0),
            burst=config.get_int("SLO_CAPTURE_BURST", 1),
            trace_s=config.get_float("SLO_CAPTURE_TRACE_S", 0.0),
            max_bundles=config.get_int("SLO_CAPTURE_MAX_BUNDLES", 32), **kw)

    # -- token bucket ----------------------------------------------------------

    def _acquire(self) -> bool:
        with self._lock:
            now = self._now()
            self._tokens = min(
                float(self.burst),
                self._tokens + (now - self._refill_at) / self.min_interval_s)
            self._refill_at = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    # -- the capture -----------------------------------------------------------

    def on_breach(self, breaches: list[dict]) -> str | None:
        """Breach-listener entrypoint: write one bundle if the bucket has a
        token, else count the suppression. Returns the bundle dir (None
        when rate-limited or the write failed)."""
        metrics = getattr(self.container, "metrics", None)
        if not self._acquire():
            if metrics is not None:
                metrics.increment_counter("app_slo_captures_suppressed_total", 1)
            return None
        try:
            path = self._write_bundle(breaches)
        except Exception as e:  # noqa: BLE001 - capture is best-effort diagnostics
            logger = getattr(self.container, "logger", None)
            if logger is not None:
                logger.warnf("slo anomaly capture failed: %r", e)
            return None
        if metrics is not None:
            metrics.increment_counter("app_slo_captures_total", 1)
        if self.trace_s > 0:
            self._start_trace(path)
        logger = getattr(self.container, "logger", None)
        if logger is not None:
            logger.warnf("slo burn breach: anomaly bundle written to %s "
                         "(%d objectives burning)", path, len(breaches))
        return path

    def _write_bundle(self, breaches: list[dict]) -> str:
        with self._lock:
            self._seq += 1
            seq = self._seq
        stamp = time.strftime("%Y%m%d-%H%M%S", time.localtime(self._clock()))
        path = os.path.join(self.out_dir, f"slo-capture-{stamp}-{seq:03d}")
        os.makedirs(path, exist_ok=True)
        flight = getattr(self.container, "flight", None)
        engines = {}
        for name, engine in getattr(self.container, "engines", {}).items():
            try:
                engines[name] = (engine.health_check()
                                 if hasattr(engine, "health_check") else {})
            except Exception as e:  # noqa: BLE001 - a broken probe is itself evidence
                engines[name] = {"status": "DOWN", "error": repr(e)}
        perf = None
        try:
            # the roofline state at breach time: was the device starved
            # (bubble) or saturated (MFU/MBU) when the burn started?
            planes = {
                name: e.perf.snapshot(time.monotonic())
                for name, e in getattr(self.container, "engines", {}).items()
                if getattr(e, "perf", None) is not None}
            perf_fn = getattr(self.container, "perf_totals", None)
            totals = perf_fn() if callable(perf_fn) else None
            if planes or totals:
                perf = {"engines": planes, "totals": totals}
        except Exception:  # noqa: BLE001 - capture is best-effort diagnostics
            perf = None
        quality = {}
        for name, e in getattr(self.container, "engines", {}).items():
            # quality-plane enrichment (metrics/quality.py): per-sample
            # replay payloads (prompt ids, emitted tokens, divergence
            # report) joined with the sampler seed, adapter digest, weights
            # epoch, kv dtype, autotune pins, and config fingerprint — the
            # complete deterministic input set scripts/replay_bundle.py
            # needs to re-execute the divergence offline
            try:
                snap_fn = getattr(e, "quality_snapshot", None)
                snap = snap_fn() if callable(snap_fn) else None
            except Exception:  # noqa: BLE001 - capture is best-effort diagnostics
                snap = None
            if snap is not None:
                quality[name] = snap
        bundle = {
            "ts": self._clock(),
            "reason": breaches,
            "slo": self.slo.snapshot(),
            "flight": {
                "requests": (flight.requests(self.flight_requests)
                             if flight is not None else []),
                "steps": (flight.steps(self.flight_steps)
                          if flight is not None else []),
            },
            "engines": engines,
            "perf": perf,
        }
        if quality:
            bundle["quality"] = quality
        with open(os.path.join(path, "bundle.json"), "w") as f:
            json.dump(bundle, f, indent=1, default=str)
        self._sweep()
        return path

    def _sweep(self) -> None:
        """Retention: drop the oldest ``slo-capture-*`` dirs beyond
        ``max_bundles``. The stamp-seq naming sorts chronologically, so a
        plain lexical sort is the age order."""
        if self.max_bundles <= 0:
            return
        try:
            names = sorted(d for d in os.listdir(self.out_dir)
                           if d.startswith("slo-capture-"))
        except OSError:
            return
        for name in names[:-self.max_bundles]:
            shutil.rmtree(os.path.join(self.out_dir, name), ignore_errors=True)

    def _start_trace(self, path: str) -> None:
        """Bounded ``jax.profiler.trace`` around the next few device steps,
        on a daemon thread (the breach was observed on a latency-critical
        path). One trace at a time; a missing/odd jax just skips it."""
        with self._lock:
            if self._tracing:
                return
            self._tracing = True

        def run() -> None:
            try:
                import jax

                with jax.profiler.trace(os.path.join(path, "trace")):
                    time.sleep(self.trace_s)
            except Exception:  # noqa: BLE001 - diagnostics only
                pass
            finally:
                with self._lock:
                    self._tracing = False

        threading.Thread(target=run, daemon=True,
                         name="gofr-slo-capture-trace").start()
