"""Metrics: name-keyed registry with Prometheus text exposition.

Capability parity with the reference's metrics manager (gofr `pkg/gofr/metrics/`):
counter / up-down counter / histogram / settable gauge registered by name
(`store.go:7-34`, `register.go:41-46`), label-cardinality warning past 20 distinct
label sets (`register.go:249-268`), and a Prometheus exposition endpoint served on
a dedicated port (`exporters/exporter.go:14-29`) that also samples process runtime
gauges per scrape (`handler.go:22-35`).

TPU-first additions: the device datasource registers ``app_tpu_hbm_bytes``,
``app_compile_cache_*`` and batch-occupancy histograms on this same registry;
the engines record the SLO latency family (``app_tpu_{queue_wait,ttft,tpot,
e2e}_seconds``, ``app_tpu_inflight_requests``) here, and the sibling
``metrics.flight`` module keeps the always-on ring of recent request
timelines and device steps behind ``/debug/requests`` / ``/debug/engine``.

Fleet federation (``metrics.federation``) reads the per-series state via the
``series()`` accessors below and the sibling ``metrics.slo`` module derives
per-class attainment/burn-rate from the same samples the SLO latency family
records — both expose through this registry's collect hooks.
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Callable, Iterable, Mapping, Sequence

LabelSet = tuple[tuple[str, str], ...]

DEFAULT_BUCKETS: tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

_CARDINALITY_WARN = 20


def _labelset(labels: Mapping[str, str] | None) -> LabelSet:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _fmt_labels(ls: LabelSet, extra: str = "") -> str:
    parts = [f'{k}="{_escape(v)}"' for k, v in ls]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, description: str):
        self.name = name
        self.description = description
        self._lock = threading.Lock()
        self._warned = False

    def expose(self) -> Iterable[str]:  # pragma: no cover - abstract
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, description: str):
        super().__init__(name, description)
        self._values: dict[LabelSet, float] = {}

    def inc(self, value: float = 1.0, **labels: str) -> None:
        ls = _labelset(labels)
        with self._lock:
            self._values[ls] = self._values.get(ls, 0.0) + value

    def expose(self) -> Iterable[str]:
        with self._lock:
            items = list(self._values.items())
        for ls, v in items or [((), 0.0)]:
            yield f"{self.name}{_fmt_labels(ls)} {_fmt_value(v)}"

    @property
    def label_cardinality(self) -> int:
        return len(self._values)

    def value(self, **labels: str) -> float:
        return self._values.get(_labelset(labels), 0.0)

    def series(self) -> list[tuple[LabelSet, float]]:
        """Consistent (labelset, value) snapshot for federation digests."""
        with self._lock:
            return list(self._values.items())


class UpDownCounter(Counter):
    kind = "gauge"  # prometheus has no up-down counter type

    def dec(self, value: float = 1.0, **labels: str) -> None:
        self.inc(-value, **labels)


class Gauge(_Metric):
    """Settable gauge (the reference emulates this over async OTel gauges;
    a plain settable value is the natural design here)."""

    kind = "gauge"

    def __init__(self, name: str, description: str):
        super().__init__(name, description)
        self._values: dict[LabelSet, float] = {}

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[_labelset(labels)] = float(value)

    def expose(self) -> Iterable[str]:
        with self._lock:
            items = list(self._values.items())
        for ls, v in items or [((), 0.0)]:
            yield f"{self.name}{_fmt_labels(ls)} {_fmt_value(v)}"

    @property
    def label_cardinality(self) -> int:
        return len(self._values)

    def value(self, **labels: str) -> float:
        return self._values.get(_labelset(labels), 0.0)

    def series(self) -> list[tuple[LabelSet, float]]:
        """Consistent (labelset, value) snapshot for federation digests."""
        with self._lock:
            return list(self._values.items())


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, description: str, buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, description)
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[LabelSet, list[int]] = {}
        self._sums: dict[LabelSet, float] = {}
        self._totals: dict[LabelSet, int] = {}

    def observe(self, value: float, **labels: str) -> None:
        ls = _labelset(labels)
        with self._lock:
            counts = self._counts.setdefault(ls, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    break
            self._sums[ls] = self._sums.get(ls, 0.0) + value
            self._totals[ls] = self._totals.get(ls, 0) + 1

    def expose(self) -> Iterable[str]:
        with self._lock:
            items = [(ls, list(c), self._sums[ls], self._totals[ls]) for ls, c in self._counts.items()]
        for ls, counts, total_sum, total in items:
            cum = 0
            for b, c in zip(self.buckets, counts):
                cum += c
                le = 'le="' + _fmt_value(b) + '"'
                yield f"{self.name}_bucket{_fmt_labels(ls, le)} {cum}"
            inf = 'le="+Inf"'
            yield f"{self.name}_bucket{_fmt_labels(ls, inf)} {total}"
            yield f"{self.name}_sum{_fmt_labels(ls)} {_fmt_value(total_sum)}"
            yield f"{self.name}_count{_fmt_labels(ls)} {total}"

    @property
    def label_cardinality(self) -> int:
        return len(self._totals)

    def count(self, **labels: str) -> int:
        return self._totals.get(_labelset(labels), 0)

    def sum(self, **labels: str) -> float:
        return self._sums.get(_labelset(labels), 0.0)

    def series(self) -> list[tuple[LabelSet, list[int], float, int]]:
        """Consistent (labelset, per-bucket counts, sum, total) snapshot.
        Counts are NON-cumulative and aligned to ``self.buckets``;
        ``total - sum(counts)`` is the +Inf overflow tail. This is the
        merge-safe form federation ships: bucket counts from replicas with
        identical ladders add element-wise, unlike percentiles."""
        with self._lock:
            return [(ls, list(c), self._sums[ls], self._totals[ls])
                    for ls, c in self._counts.items()]


class Registry:
    """Name-keyed metric store with exposition (gofr `metrics/store.go`)."""

    def __init__(self, logger=None):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()
        self._logger = logger
        self._collect_hooks: list[Callable[["Registry"], None]] = []

    # -- registration ----------------------------------------------------------

    def new_counter(self, name: str, description: str = "") -> Counter:
        return self._register(name, lambda: Counter(name, description), Counter)

    def new_updown_counter(self, name: str, description: str = "") -> UpDownCounter:
        return self._register(name, lambda: UpDownCounter(name, description), UpDownCounter)

    def new_gauge(self, name: str, description: str = "") -> Gauge:
        return self._register(name, lambda: Gauge(name, description), Gauge)

    def new_histogram(
        self, name: str, description: str = "", buckets: Sequence[float] = DEFAULT_BUCKETS
    ) -> Histogram:
        return self._register(name, lambda: Histogram(name, description, buckets), Histogram)

    def _register(self, name: str, factory, cls):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                # exact type match: Counter vs UpDownCounter are NOT interchangeable
                if type(existing) is not cls:
                    raise ValueError(
                        f"metric {name!r} already registered as {type(existing).__name__}"
                    )
                return existing
            metric = factory()
            self._metrics[name] = metric
            return metric

    def get(self, name: str) -> _Metric | None:
        return self._metrics.get(name)

    # -- recording by name (container-facing API, mirrors gofr Metrics iface) --

    def increment_counter(self, name: str, value: float = 1.0, **labels: str) -> None:
        m = self._metrics.get(name)
        if isinstance(m, Counter):
            m.inc(value, **labels)
            self._warn_cardinality(m)

    def delta_updown_counter(self, name: str, value: float, **labels: str) -> None:
        """Apply a signed delta to an up-down counter (gofr
        `DeltaUpDownCounter` parity)."""
        m = self._metrics.get(name)
        if isinstance(m, UpDownCounter):
            m.inc(value, **labels)
            self._warn_cardinality(m)

    def set_gauge(self, name: str, value: float, **labels: str) -> None:
        m = self._metrics.get(name)
        if isinstance(m, Gauge):
            m.set(value, **labels)
            self._warn_cardinality(m)

    def record_histogram(self, name: str, value: float, **labels: str) -> None:
        m = self._metrics.get(name)
        if isinstance(m, Histogram):
            m.observe(value, **labels)
            self._warn_cardinality(m)

    def _warn_cardinality(self, m: _Metric) -> None:
        card = getattr(m, "label_cardinality", 0)
        if card > _CARDINALITY_WARN and not m._warned:
            m._warned = True
            if self._logger is not None:
                self._logger.warnf(
                    "metric %s has high label cardinality (%d > %d); consider fewer label values",
                    m.name, card, _CARDINALITY_WARN,
                )

    # -- exposition ------------------------------------------------------------

    def add_collect_hook(self, hook: Callable[["Registry"], None]) -> None:
        """Hook invoked on every scrape (runtime/HBM gauges sample here)."""
        self._collect_hooks.append(hook)

    def remove_collect_hook(self, hook: Callable[["Registry"], None]) -> None:
        """Unregister a scrape hook (no-op if absent) — replacing a
        component (e.g. re-enabling QoS) must not leave its stale sampler
        writing gauges on every scrape."""
        try:
            self._collect_hooks.remove(hook)
        except ValueError:
            pass

    def expose_text(self) -> str:
        for hook in list(self._collect_hooks):
            try:
                hook(self)
            except Exception:  # noqa: BLE001 - a bad hook must not break /metrics
                pass
        lines: list[str] = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            if m.description:
                lines.append(f"# HELP {m.name} {m.description}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


def sample_runtime_metrics(registry: Registry) -> None:
    """Per-scrape process gauges (analog of gofr's memstats/goroutine sampling,
    `metrics/handler.go:22-35`)."""
    g_threads = registry.new_gauge("app_threads", "live python threads")
    g_rss = registry.new_gauge("app_sys_memory_rss_bytes", "resident set size")
    g_uptime = registry.new_gauge("app_uptime_seconds", "seconds since process start")
    g_threads.set(threading.active_count())
    g_rss.set(_rss_bytes())
    g_uptime.set(time.monotonic() - _START)


_START = time.monotonic()


def _rss_bytes() -> int:
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * (os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096)
    except (OSError, ValueError, IndexError):
        return 0
