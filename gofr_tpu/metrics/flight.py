"""Flight recorder: always-on ring buffers of recent serving activity.

Metrics aggregate and traces need a backend attached *before* the incident
— this is the third leg: the engines append every completed request's
timeline (queue wait, TTFT, TPOT, e2e, slot, preemptions, trace id) and
every device step (kind, wall time, occupancy, signature) into two bounded
deques, so ``GET /debug/requests`` / ``GET /debug/engine`` can answer
"what just happened" on a production box with nothing but curl.

The online controller (gofr_tpu.control) adds a third ring: every
try/commit/revert/resume/standdown decision lands in ``record_control``
(served by ``GET /debug/control``), and step entries carry the active
knob vector — so an anomaly bundle shows not just what the step did but
which tuning it ran under, and a decision can be lined up against the
steps it judged.

Cost discipline: one uncontended lock acquisition + a dict append per
completed request / device step — never per token. The lock exists only
because ``list(deque)`` raises if another thread appends mid-iteration;
appends themselves are O(1) with bounded memory (maxlen evicts oldest).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any


class FlightRecorder:
    def __init__(self, max_requests: int = 256, max_steps: int = 512,
                 max_controls: int = 128):
        self._requests: collections.deque[dict[str, Any]] = collections.deque(
            maxlen=max(1, int(max_requests)))
        self._steps: collections.deque[dict[str, Any]] = collections.deque(
            maxlen=max(1, int(max_steps)))
        self._controls: collections.deque[dict[str, Any]] = collections.deque(
            maxlen=max(1, int(max_controls)))
        self._lock = threading.Lock()

    # -- recording (engine side) -----------------------------------------------

    def record_request(self, entry: dict[str, Any]) -> None:
        with self._lock:
            self._requests.append(entry)

    def record_step(self, kind: str, seconds: float, occupancy: float,
                    signature: Any, backlog: int = 0, inflight: int = 0,
                    device_s: float | None = None, bytes_: float | None = None,
                    flops: float | None = None,
                    bubble_s: float | None = None,
                    knobs: dict[str, Any] | None = None) -> None:
        # With the unified async pipeline, steps are recorded at COMPLETION
        # (dequeue) time; `seconds` spans dispatch→fold and `inflight` is
        # the in-flight queue depth left after this entry was dequeued —
        # 0 on every step means the pipeline is running synchronously.
        # The perf plane (metrics/perf.py) adds the roofline view per step:
        # `device_s` is overlap-deduplicated device-queue residency,
        # `bytes`/`flops` the analytical cost from the step's actual
        # shapes, `bubble` the device-idle-while-work-queued gap in front.
        entry = {
            "at": time.time(),
            "kind": kind,
            "seconds": round(float(seconds), 6),
            "occupancy": round(float(occupancy), 4),
            "signature": str(signature),
            "backlog": int(backlog),
            "inflight": int(inflight),
        }
        if device_s is not None:
            entry["device_s"] = round(float(device_s), 6)
            entry["bytes"] = float(bytes_ or 0.0)
            entry["flops"] = float(flops or 0.0)
            entry["bubble"] = round(float(bubble_s or 0.0), 6)
        if knobs:
            entry["knobs"] = dict(knobs)
        with self._lock:
            self._steps.append(entry)

    def record_control(self, decision: dict[str, Any]) -> None:
        """One controller decision (already to_dict()-flattened)."""
        with self._lock:
            self._controls.append(decision)

    # -- inspection (debug endpoints / tests) ----------------------------------

    def requests(self, limit: int | None = None) -> list[dict[str, Any]]:
        """Completed request timelines, newest first."""
        with self._lock:
            out = list(self._requests)
        out.reverse()
        return out[:limit] if limit else out

    def steps(self, limit: int | None = None) -> list[dict[str, Any]]:
        """Device steps, newest first."""
        with self._lock:
            out = list(self._steps)
        out.reverse()
        return out[:limit] if limit else out

    def controls(self, limit: int | None = None) -> list[dict[str, Any]]:
        """Controller decisions, newest first."""
        with self._lock:
            out = list(self._controls)
        out.reverse()
        return out[:limit] if limit else out
