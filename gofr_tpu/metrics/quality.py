"""Online numerics/quality plane: shadow-score sampled traffic against a
golden reference configuration (docs/observability.md "Quality plane").

The serving stack answers *how fast* everywhere (tracing, SLO, perf
rooflines) but nothing answers *is the math still right*: int8/int4 KV
with fused dequant, autotuner-pinned kernels, LoRA deltas and live weight
hot-swap all produce plausible-looking tokens when they drift. This module
closes that gap with a teacher-forced shadow scorer:

- For a sampled fraction of completed requests (``QUALITY_SHADOW_RATE``),
  the request's *exact emitted token sequence* is re-scored — no
  re-sampling, so the check is deterministic by construction — through two
  configurations:

  * the **serving arm**: base weights + the live KV dtype's fake-quant
    round-trip (ops/kvcache.fake_quant_row for int8, ops/quant.
    fake_quant_row_int4 for int4 — the exact scale-dtype semantics the
    pool stores) + the request's LoRA head delta;
  * the **reference arm**: slot-0 base weights, dense bf16 KV via the
    plain XLA attention path, no adapter.

- Per-token divergence rolls up into ``app_tpu_quality_{logprob_delta,
  kl,top1_agree}`` keyed by what the serving path actually used
  (``kv_dtype``, ``backend``, ``adapter``), a first-divergence-token-index
  histogram, and summable good/total counters that ride the gossip digest
  (metrics/federation.py) for exact sum-of-parts fleet rollups.

- Each scored sample keeps a bounded replay payload (prompt ids, emitted
  tokens, divergence report) that the SLO CaptureWatcher joins into
  anomaly bundles; ``scripts/replay_bundle.py`` re-executes them offline.

Scoring runs on the engine device thread only during idle loop iterations
— one bounded forward per iteration, re-checking the interactive backlog
between arms — and claims no decode slots or KV pages, so interactive
traffic always wins and the plane can never leak pool state. With the
rate at 0 (the default) the plane is never constructed and the engine is
bit-identical to the pre-quality build.
"""

from __future__ import annotations

import collections
import random
import threading
import time
from typing import Any, Callable

import numpy as np

__all__ = [
    "QualityPlane",
    "divergence_report",
    "make_adapter_head_fn",
    "make_serving_attn_fn",
    "teacher_forced_rows",
]


# -- pure scoring helpers ------------------------------------------------------


def _pow2_bucket(n: int, max_len: int) -> int:
    """Pad shadow sequences to a power-of-two ladder (min 16) so the
    teacher-forced forward compiles O(log max_len) signatures, not one per
    request length — the same discipline as the engine's prefill buckets."""
    b = 16
    while b < n:
        b *= 2
    return max(n, min(b, max_len)) if max_len else b


_ATTN_CACHE: dict[str, Any] = {}


def make_serving_attn_fn(kv_dtype: str):
    """Attention wrapper reproducing the live KV pool's quantization on the
    teacher-forced path: k/v round-trip through the pool's exact row-quant
    + scale-dtype semantics before attention. Returns None for the dense
    pool (the serving arm IS the reference attention there). Cached per
    dtype so every call reuses one function object — jit retraces once."""
    kv_dtype = kv_dtype or "bf16"
    if kv_dtype in ("", "bf16", "dense"):
        return None
    if kv_dtype in _ATTN_CACHE:
        return _ATTN_CACHE[kv_dtype]
    from gofr_tpu.ops.attention import mha_attention

    if kv_dtype == "int8":
        from gofr_tpu.ops.kvcache import fake_quant_row as _fq
    elif kv_dtype == "int4":
        from gofr_tpu.ops.quant import fake_quant_row_int4 as _fq
    else:
        raise ValueError(f"unknown kv_dtype {kv_dtype!r}: use bf16, int8 or int4")

    def attn(q, k, v, *, causal=True, kv_lengths=None):
        return mha_attention(q, _fq(k), _fq(v), causal=causal,
                             kv_lengths=kv_lengths)

    _ATTN_CACHE[kv_dtype] = attn
    return attn


def make_adapter_head_fn(a: np.ndarray, b: np.ndarray, scale: float):
    """lm_head hook adding the request's LoRA delta exactly as serving does
    (ops/lora.lora_logits_delta f32 math over a one-slot pool): base logits
    in model dtype + f32 low-rank delta — promotion is exact, so a zero
    delta keeps the base path bit-identical."""
    import jax.numpy as jnp

    from gofr_tpu.ops.lora import lora_logits_delta
    from gofr_tpu.ops.quant import qdot

    pool = (jnp.zeros((1,), jnp.int32),
            jnp.asarray(a, jnp.float32)[None],
            jnp.asarray(b, jnp.float32)[None],
            jnp.asarray([float(scale)], jnp.float32))

    def head_fn(x, head):
        # x [B,S,E] maps onto lora_logits_delta's [N,T,E] verify layout
        return qdot(x, head) + lora_logits_delta(x, pool)

    return head_fn


def teacher_forced_rows(family, cfg, params, prompt, emitted, *,
                        attn_fn=None, head_fn=None) -> np.ndarray:
    """Teacher-forced logits over the emitted positions: feed the full
    ``prompt + emitted`` sequence through ``family.forward`` (padded to a
    pow2 bucket, lengths-masked) and slice the rows that *predicted* each
    emitted token — rows ``[len(prompt)-1, len(prompt)-1+T)``. Returns
    f32 ``[T, vocab]``. Deterministic: same inputs → same bucket → same
    compiled program → bitwise-identical rows."""
    import jax.numpy as jnp

    seq = list(map(int, prompt)) + list(map(int, emitted))
    n = len(seq)
    t = len(emitted)
    if t < 1 or len(prompt) < 1:
        raise ValueError("teacher-forced scoring needs >=1 prompt and emitted token")
    bucket = _pow2_bucket(n, int(getattr(cfg, "max_seq_len", 0)))
    padded = np.zeros((1, bucket), np.int32)
    padded[0, :n] = np.asarray(seq, np.int32)
    lengths = jnp.asarray([n], jnp.int32)
    logits = family.forward(cfg, params, jnp.asarray(padded), lengths,
                            attn_fn, head_fn)
    lo = len(prompt) - 1
    return np.asarray(logits[0, lo:lo + t], np.float32)


def _log_softmax(rows: np.ndarray) -> np.ndarray:
    z = rows.astype(np.float64)
    z = z - z.max(axis=-1, keepdims=True)
    return z - np.log(np.exp(z).sum(axis=-1, keepdims=True))


def divergence_report(serving_rows: np.ndarray, ref_rows: np.ndarray,
                      emitted) -> dict[str, Any]:
    """Per-token divergence between the serving-configuration re-score and
    the reference re-score of one emitted sequence.

    - ``logprob_delta``: serving minus reference log-prob of each emitted
      token (mean/max absolute values reported);
    - ``kl``: KL(serving ‖ reference) per position;
    - ``top1_agree``: fraction of positions where the REFERENCE argmax
      equals the token the live engine actually emitted — this compares
      the golden path against production output, so it catches live
      corruption the re-score arms cannot reproduce (e.g. a miscompiled
      decode kernel);
    - ``first_divergence``: first position whose reference argmax
      disagrees with the emitted token (-1 = full agreement);
    - ``agree``: the per-token agreement mask, kept for offline replay
      diffing (scripts/replay_bundle.py matches it token-by-token).
    """
    emitted = np.asarray(list(emitted), np.int64)
    t = emitted.shape[0]
    ls = _log_softmax(serving_rows)
    lr = _log_softmax(ref_rows)
    idx = np.arange(t)
    delta = ls[idx, emitted] - lr[idx, emitted]
    kl = (np.exp(ls) * (ls - lr)).sum(axis=-1)
    ref_top1 = lr.argmax(axis=-1)
    agree = ref_top1 == emitted
    first = int(np.argmax(~agree)) if not agree.all() else -1
    return {
        "tokens": int(t),
        "logprob_delta_mean_abs": float(np.abs(delta).mean()),
        "logprob_delta_max_abs": float(np.abs(delta).max()),
        "kl_mean": float(np.maximum(kl, 0.0).mean()),
        "kl_max": float(np.maximum(kl, 0.0).max()),
        "top1_agree": float(agree.mean()),
        "first_divergence": first,
        "agree": [int(x) for x in agree],
    }


# -- the plane -----------------------------------------------------------------


class QualityPlane:
    """Per-engine shadow-scoring state machine.

    ``maybe_capture`` (device thread, request completion) samples finished
    requests into a bounded pending queue — drop-oldest under pressure,
    counted, never blocking. ``step`` (device thread, idle loop) advances
    ONE arm of one sample per call and reports whether it did work, so the
    loop re-checks the interactive backlog between forwards. ``snapshot``
    (any thread) serves /debug/quality and capture-bundle enrichment."""

    def __init__(self, family, cfg, params_fn: Callable[[], Any], *,
                 metrics=None, slo=None, rate: float = 0.0, seed: int = 0,
                 kv_dtype: str = "bf16", backend_fn: Callable[[], str] | None = None,
                 adapter_fn: Callable[[str], tuple | None] | None = None,
                 max_pending: int = 16, max_tokens: int = 64,
                 top1_min: float = 0.9, kl_max: float = 1.0,
                 recent: int = 32):
        self.family = family
        self.cfg = cfg
        self.params_fn = params_fn
        self.metrics = metrics
        self.slo = slo
        self.rate = max(0.0, min(1.0, float(rate)))
        self.kv_dtype = kv_dtype or "bf16"
        self.backend_fn = backend_fn
        self.adapter_fn = adapter_fn
        self.max_pending = max(1, int(max_pending))
        self.max_tokens = max(1, int(max_tokens))
        self.top1_min = float(top1_min)
        self.kl_max = float(kl_max)
        # seeded sampling: a given seed replays the same shadow schedule
        self._rng = random.Random((int(seed) << 1) ^ 0x9E3779B9)
        self._pending: collections.deque = collections.deque()
        self._inflight: dict[str, Any] | None = None
        self._recent: collections.deque = collections.deque(maxlen=max(1, int(recent)))
        self._lock = threading.Lock()
        self.samples = 0   # fully scored
        self.good = 0      # scored and within thresholds
        self.dropped = 0   # sampled but evicted from the pending queue
        self.errors = 0    # scoring failures (never propagate to serving)
        # per-adapter head_fn cache: head_fn is a STATIC jit arg, so reusing
        # one function object per (adapter, factors) identity keeps repeat
        # samples of the same adapter from retracing the forward
        self._head_cache: dict[str, tuple[tuple, Any]] = {}

    # -- capture (request completion path) ---------------------------------

    def maybe_capture(self, prompt_tokens, emitted, *, adapter: str | None = None,
                      qos_class: str | None = None, weights_epoch: int = 0,
                      request_id: str | None = None) -> bool:
        """Roll the sampling dice for one finished request; when selected,
        enqueue a shadow-scoring sample. O(prompt) copy at most — all
        device work happens later, on idle iterations."""
        if self.rate <= 0.0 or len(emitted) < 1 or len(prompt_tokens) < 1:
            return False
        if self.rate < 1.0 and self._rng.random() >= self.rate:
            return False
        sample = {
            "request_id": request_id,
            "prompt": [int(x) for x in prompt_tokens],
            "emitted": [int(x) for x in emitted[: self.max_tokens]],
            "emitted_total": int(len(emitted)),
            "adapter": adapter,
            "qos_class": qos_class,
            "weights_epoch": int(weights_epoch),
            "ts": time.time(),
        }
        if adapter and self.adapter_fn is not None:
            # resolve the LoRA factors NOW — the registry entry may be
            # replaced before the idle loop gets to scoring
            sample["_adapter_factors"] = self.adapter_fn(adapter)
        with self._lock:
            self._pending.append(sample)
            while len(self._pending) > self.max_pending:
                self._pending.popleft()
                self.dropped += 1
                if self.metrics is not None:
                    self.metrics.increment_counter(
                        "app_tpu_quality_shadow_dropped_total", 1)
        return True

    @property
    def pending(self) -> int:
        with self._lock:
            n = len(self._pending)
        return n + (1 if self._inflight is not None else 0)

    # -- scoring (engine idle loop) ----------------------------------------

    def step(self) -> bool:
        """Advance one arm of one sample. Returns True when device work was
        done (the caller should re-check its backlog before calling again).
        Failures are counted and the sample dropped — the quality plane
        must never take the serving loop down with it."""
        s = self._inflight
        if s is None:
            with self._lock:
                if not self._pending:
                    return False
                s = self._inflight = self._pending.popleft()
        try:
            if "_serving_rows" not in s:
                s["_serving_rows"] = self._score(s, serving=True)
                return True
            ref_rows = self._score(s, serving=False)
            self._finalize(s, s.pop("_serving_rows"), ref_rows)
        except Exception:  # noqa: BLE001 - diagnostics plane, never fatal
            with self._lock:
                self.errors += 1
            self._inflight = None
        else:
            if "_serving_rows" not in s:
                self._inflight = None
        return True

    def _score(self, s: dict[str, Any], *, serving: bool) -> np.ndarray:
        params = self.params_fn()
        attn_fn = make_serving_attn_fn(self.kv_dtype) if serving else None
        head_fn = None
        if serving:
            factors = s.get("_adapter_factors")
            if factors is not None:
                a, b, scale = factors
                key = (id(a), id(b), float(scale))
                cached = self._head_cache.get(s["adapter"])
                if cached is None or cached[0] != key:
                    cached = (key, make_adapter_head_fn(a, b, scale))
                    self._head_cache[s["adapter"]] = cached
                head_fn = cached[1]
        return teacher_forced_rows(
            self.family, self.cfg, params, s["prompt"], s["emitted"],
            attn_fn=attn_fn, head_fn=head_fn)

    def _finalize(self, s: dict[str, Any], serving_rows: np.ndarray,
                  ref_rows: np.ndarray) -> None:
        report = divergence_report(serving_rows, ref_rows, s["emitted"])
        ok = (report["top1_agree"] >= self.top1_min
              and report["kl_mean"] <= self.kl_max)
        labels = {
            "kv_dtype": self.kv_dtype,
            "backend": self.backend_fn() if self.backend_fn is not None else "xla",
            "adapter": s.get("adapter") or "base",
        }
        m = self.metrics
        if m is not None:
            m.record_histogram("app_tpu_quality_logprob_delta",
                               report["logprob_delta_mean_abs"], **labels)
            m.record_histogram("app_tpu_quality_kl", report["kl_mean"], **labels)
            m.set_gauge("app_tpu_quality_top1_agree", report["top1_agree"],
                        **labels)
            if report["first_divergence"] >= 0:
                m.record_histogram("app_tpu_quality_first_divergence_token",
                                   report["first_divergence"], **labels)
            m.increment_counter("app_tpu_quality_samples_total", 1, **labels)
            if ok:
                m.increment_counter("app_tpu_quality_good_total", 1, **labels)
        if self.slo is not None:
            observe = getattr(self.slo, "observe_quality", None)
            if callable(observe):
                observe(s.get("qos_class"), ok)
        entry = {k: v for k, v in s.items() if not k.startswith("_")}
        entry["labels"] = labels
        entry["ok"] = ok
        entry["report"] = report
        with self._lock:
            self.samples += 1
            if ok:
                self.good += 1
            self._recent.append(entry)

    # -- host-side helpers --------------------------------------------------

    def drain(self, timeout: float = 30.0) -> bool:
        """Block (host thread) until the engine's idle loop has scored every
        pending sample, or the timeout passes. Test/bench helper only."""
        deadline = time.monotonic() + timeout
        while self.pending:
            if time.monotonic() > deadline:
                return False
            time.sleep(0.01)
        return True

    def snapshot(self, *, replay: bool = True) -> dict[str, Any]:
        """The /debug/quality + capture-bundle view: plane totals plus the
        recent per-sample reports (with replay payloads unless trimmed)."""
        with self._lock:
            recent = list(self._recent)
            out = {
                "rate": self.rate,
                "kv_dtype": self.kv_dtype,
                "pending": len(self._pending) + (1 if self._inflight else 0),
                "samples": self.samples,
                "good": self.good,
                "dropped": self.dropped,
                "errors": self.errors,
                "thresholds": {"top1_min": self.top1_min, "kl_max": self.kl_max},
            }
        if not replay:
            recent = [{k: v for k, v in e.items()
                       if k not in ("prompt", "emitted")} for e in recent]
        out["recent"] = recent
        return out
