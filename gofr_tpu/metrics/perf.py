"""Live engine performance plane: per-step roofline attribution.

The serving engine was blind to its own speed — the only MFU/MBU numbers
came from ``bench.py``'s coarse whole-run estimate (param bytes only, no
KV-pool traffic, no per-step-kind breakdown), and the last two bench
rounds silently ran on CPU. This module is the continuously-on fix: an
analytical per-step cost model (FLOPs + HBM bytes from the step's
ACTUAL shapes) divided by measured per-step device time against a
``device_kind -> (peak FLOPs, peak HBM bytes/s)`` table, yielding live
windowed ``app_tpu_{mfu,mbu}{kind,kv_dtype}`` gauges, per-kind device-
time histograms, and a ``_dq`` pipeline-bubble ratio (the direct health
check on the unified-pipeline overlap design).

Three design rules keep the plane honest:

* **Exact bytes, not nominal dtypes.** The per-position KV footprint is
  read off the live pool leaves (``sum(leaf.nbytes) / positions``) so it
  reproduces the archived 512/144/80 bf16/int8/int4 plane accounting
  bit-for-bit — on CPU the "bf16" pool is physically fp32, and a nominal
  2-byte assumption would silently disagree with the pool by 2x.
  :func:`kv_plane_bytes_per_position` (ops/paged.py) is the analytic
  cross-check used by tests and by bench before an engine exists.
* **Sum parts, never average ratios.** Every merge point (engines in one
  container, replicas in the fleet digest) sums FLOPs/bytes numerators
  and ``device_s * peak`` capacity denominators; the ratio is derived
  once, at the edge. ``aggregate([a, b]) == aggregate([a + b])`` exactly.
* **One estimator.** ``bench.py``'s ``mbu_decode_lb`` is re-derived from
  :func:`decode_lb_bytes` here, so serving and bench can never disagree
  about what the lower bound counts.

FLOPs convention: ``2 * n_params * tokens`` (the forward-pass MAC
count bench has always used). Attention FLOPs are *excluded* — on the
decode path they are bandwidth, not compute, which is exactly why the
bytes side DOES count the streamed history. MFU here is therefore a
slight *under*-estimate at long context; MBU is the honest number this
plane exists for (ROADMAP O3).

Peak resolution order (first hit wins), per component:

1. ``GOFR_TPU_PEAK_TFLOPS`` / ``GOFR_TPU_PEAK_GBS`` — operator says so.
2. ``GOFR_DEVICE_PEAKS`` — JSON ``{"kind-substring": [tflops, gbs]}``
   for silicon the builtin table hasn't met yet.
3. The builtin table (spec-sheet bf16 FLOPs / HBM bandwidth). The
   ``cpu`` entry is a NOMINAL reference envelope (1 TFLOP/s, 50 GB/s)
   so CPU smoke runs exercise the full plane end to end; it is not a
   hardware claim and is labelled ``nominal`` wherever it surfaces.
4. Unknown device, no override: peaks degrade to ``None`` — utilization
   gauges go unreported rather than wrong; raw FLOPs/bytes/seconds still
   flow.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Iterable

# spec-sheet peaks: bf16 FLOPs/s and HBM bytes/s per chip. Substring
# match on jax's device_kind ("TPU v5e" / "TPU v5 lite" etc), longest
# key first so "v5p" wins over "v5".
DEFAULT_PEAKS: dict[str, tuple[float, float]] = {
    "v6e": (918e12, 1638e9),
    "v6": (918e12, 1638e9),
    "v5p": (459e12, 2765e9),
    "v5e": (197e12, 819e9),
    "v5 lite": (197e12, 819e9),
    "v5": (459e12, 2765e9),
    "v4": (275e12, 1228e9),
    "v3": (123e12, 900e9),
    # NOMINAL envelope for CPU smokes (see module docstring) — makes the
    # full MFU/MBU plane light up under tests/CI without real silicon.
    "cpu": (1e12, 50e9),
}


def device_peaks(device_kind: str) -> tuple[float, float] | None:
    """Resolve ``device_kind`` to ``(peak_flops_per_s, peak_hbm_bytes_per_s)``
    or None for unknown silicon (resolution order in the module docstring).
    Env is read per call — tests and late operator overrides both want
    that, and this runs at scrape/snapshot cadence, never per step."""
    kind = (device_kind or "").lower()
    flops = bw = None
    table = dict(DEFAULT_PEAKS)
    raw = os.environ.get("GOFR_DEVICE_PEAKS", "")
    if raw:
        try:
            for k, v in json.loads(raw).items():
                table[str(k).lower()] = (float(v[0]) * 1e12, float(v[1]) * 1e9)
        except (ValueError, TypeError, IndexError, KeyError):
            pass  # a malformed override must not take the plane down
    for key in sorted(table, key=len, reverse=True):
        if key in kind:
            flops, bw = table[key]
            break
    env_f = os.environ.get("GOFR_TPU_PEAK_TFLOPS")
    if env_f:
        try:
            flops = float(env_f) * 1e12
        except ValueError:
            pass
    env_b = os.environ.get("GOFR_TPU_PEAK_GBS")
    if env_b:
        try:
            bw = float(env_b) * 1e9
        except ValueError:
            pass
    if flops is None or bw is None:
        return None
    return flops, bw


# -- occupancy bands (step controller evidence keys) -------------------------

# Slot-occupancy bands the control plane buckets evidence by: a knob that
# wins at a packed batch ("hi") can lose at a near-empty one ("lo"), so
# pins are per band. Boundaries are coarse on purpose — finer bands would
# starve each bucket of evidence at the controller's tick cadence.
OCCUPANCY_BANDS: tuple[tuple[str, float], ...] = (
    ("lo", 0.35), ("mid", 0.70), ("hi", float("inf")))


def occupancy_band(occupancy: float | None) -> str:
    """Map a step's batch occupancy (0..1) to its evidence band."""
    occ = 0.0 if occupancy is None else float(occupancy)
    for name, hi in OCCUPANCY_BANDS:
        if occ < hi:
            return name
    return OCCUPANCY_BANDS[-1][0]


# -- shared bench/engine estimator ------------------------------------------


def decode_lb_bytes(*, weight_bytes: float, new_tokens: int, slots: int,
                    kv_bytes_per_pos: float, hist_len: int) -> float:
    """Lower bound on HBM bytes the decode phase must stream to produce
    ``new_tokens`` at batch width ``slots``: the weights once per device
    micro-step (``new_tokens / slots`` of them at best), plus each
    token's attention read of at least ``hist_len`` cached positions,
    plus its own KV write. ``hist_len`` should be a *floor* on the
    context length (the prompt length is the honest choice — history
    only grows). This is THE estimator: bench's ``mbu_decode_lb`` and
    the live plane's decode bytes both derive from these terms, so the
    two can never disagree about what the bound counts."""
    steps = new_tokens / max(1, slots)
    kv_read = float(new_tokens) * float(hist_len) * float(kv_bytes_per_pos)
    kv_write = float(new_tokens) * float(kv_bytes_per_pos)
    return float(weight_bytes) * steps + kv_read + kv_write


def mbu_decode_lb(*, weight_bytes: float, new_tokens: int, slots: int,
                  kv_bytes_per_pos: float, hist_len: int,
                  elapsed_s: float, peak_bw: float) -> float:
    """Decode-MBU lower bound from :func:`decode_lb_bytes`."""
    return decode_lb_bytes(
        weight_bytes=weight_bytes, new_tokens=new_tokens, slots=slots,
        kv_bytes_per_pos=kv_bytes_per_pos, hist_len=hist_len,
    ) / max(elapsed_s, 1e-12) / max(peak_bw, 1e-12)


def mbu_decode_lb_params(*, weight_bytes: float, new_tokens: int, slots: int,
                         elapsed_s: float, peak_bw: float) -> float:
    """The PRE-perf-plane bound (weights only, no KV-pool traffic) —
    kept so the archived bench trajectory stays comparable across the
    estimator change (`mbu_decode_lb_params` field)."""
    return (float(weight_bytes) * float(new_tokens) / max(1, slots)
            / max(elapsed_s, 1e-12) / max(peak_bw, 1e-12))


# -- per-step cost model -----------------------------------------------------


class CostModel:
    """Analytical FLOPs/bytes for one engine's step kinds, from the
    engine's ACTUAL geometry: parameter count/bytes (post-quantization),
    the exact per-position KV-pool footprint, and the paged-pool page
    byte size. Pure arithmetic — every method is safe under any lock."""

    __slots__ = ("n_params", "weight_bytes", "kv_bytes_per_pos",
                 "page_bytes", "page_size", "kv_dtype", "kv_shards")

    def __init__(self, *, n_params: float, weight_bytes: float,
                 kv_bytes_per_pos: float, page_bytes: float = 0.0,
                 page_size: int = 0, kv_dtype: str = "bf16",
                 kv_shards: int = 1):
        self.n_params = float(n_params)
        self.weight_bytes = float(weight_bytes)
        # on a tp-sharded pool the engine passes PER-DEVICE byte figures
        # (1/kv_shards of the logical planes): every roofline this model
        # prices is a per-device bound, and the fleet rollup sums parts
        self.kv_bytes_per_pos = float(kv_bytes_per_pos)
        self.page_bytes = float(page_bytes)
        self.page_size = int(page_size)
        self.kv_dtype = kv_dtype or "bf16"
        self.kv_shards = max(1, int(kv_shards))

    def prefill(self, tokens: int) -> tuple[float, float]:
        """Batched prefill of ``tokens`` real prompt tokens (padding
        excluded): one weight pass + every position's KV write."""
        flops = 2.0 * self.n_params * tokens
        bytes_ = self.weight_bytes + tokens * self.kv_bytes_per_pos
        return flops, bytes_

    def chunk(self, chunk: int, offset: int) -> tuple[float, float]:
        """One prefill chunk at ``offset``: the chunk's weight pass and
        KV writes, plus the attention re-read of everything cached so
        far (chunked prefill's extra bandwidth cost vs one-shot)."""
        flops = 2.0 * self.n_params * chunk
        bytes_ = (self.weight_bytes
                  + (offset + chunk) * self.kv_bytes_per_pos   # attn read
                  + chunk * self.kv_bytes_per_pos)             # writes
        return flops, bytes_

    def decode(self, lanes: int, k: int, hist_positions: int) -> tuple[float, float]:
        """One decode chunk: ``k`` sequential micro-steps over ``lanes``
        lanes. Weights stream once per micro-step; each micro-step's
        attention reads the lanes' combined history (``hist_positions``
        — pages-touched * page_size on paged, positions on slot, a
        dispatch-time floor since history grows within the chunk); each
        emitted token writes its KV row."""
        flops = 2.0 * self.n_params * lanes * k
        bytes_ = (k * self.weight_bytes
                  + k * hist_positions * self.kv_bytes_per_pos
                  + lanes * k * self.kv_bytes_per_pos)
        return flops, bytes_

    def spec(self, lanes: int, k: int, g: int,
             hist_positions: int) -> tuple[float, float]:
        """One speculative round: ``k`` micro-steps, each verifying
        ``g`` drafts + 1 bonus position per lane on the target — the
        work is done for every proposed position whether or not the
        fold accepts it (rejection waste shows up as MFU spent without
        tokens emitted, which is the point of metering it)."""
        flops = 2.0 * self.n_params * lanes * k * (g + 1)
        bytes_ = (k * self.weight_bytes
                  + k * hist_positions * self.kv_bytes_per_pos
                  + lanes * k * (g + 1) * self.kv_bytes_per_pos)
        return flops, bytes_

    def swapin(self, nbytes: float) -> tuple[float, float]:
        """Host->device page upload: pure transfer, no FLOPs."""
        return 0.0, float(nbytes)

    def handoff_export(self, pages: int) -> tuple[float, float]:
        """Device->host gather of ``pages`` pool pages for a prefill-
        role KV handoff: pure transfer, no FLOPs."""
        return 0.0, pages * self.page_bytes

    def describe(self) -> dict[str, float | str]:
        return {
            "n_params": self.n_params,
            "weight_bytes": self.weight_bytes,
            "kv_bytes_per_pos": round(self.kv_bytes_per_pos, 6),
            "page_bytes": self.page_bytes,
            "page_size": self.page_size,
            "kv_dtype": self.kv_dtype,
            "kv_shards": self.kv_shards,
        }


class StepPerf:
    """One dispatched device call's perf record: cost filled at dispatch
    from the step's actual shapes, timestamps stamped along the ``_dq``
    lifecycle (``t_dispatch`` at dispatch, ``t_ready`` right after the
    blocking readback), residency derived at fold by
    :meth:`PerfPlane.note` — ``device_s`` is the step's device-queue
    residency with pipeline overlap deduplicated, ``bubble_s`` the
    device-idle-while-work-queued gap in front of it."""

    __slots__ = ("kind", "flops", "bytes", "t_dispatch", "t_ready",
                 "device_s", "bubble_s", "fold_s")

    def __init__(self, kind: str, flops: float, bytes_: float, t_dispatch: float):
        self.kind = kind
        self.flops = float(flops)
        self.bytes = float(bytes_)
        self.t_dispatch = float(t_dispatch)
        self.t_ready: float | None = None
        self.device_s: float = 0.0
        self.bubble_s: float = 0.0
        self.fold_s: float = 0.0


class _SumRing:
    """Windowed float sums: ``buckets`` slots of ``width`` seconds each,
    recycled by epoch stamp (the slo.py ``_WindowRing`` discipline — no
    timers, O(buckets) on read, O(1) on write)."""

    __slots__ = ("_width", "_buckets", "_sums", "_epoch")

    def __init__(self, window_s: float, buckets: int = 30):
        self._width = max(window_s, 1e-6) / buckets
        self._buckets = buckets
        self._sums: list[dict[str, float]] = [{} for _ in range(buckets)]
        self._epoch = [-1] * buckets

    def add(self, now: float, **vals: float) -> None:
        idx = int(now / self._width)
        slot = idx % self._buckets
        if self._epoch[slot] != idx:
            self._epoch[slot] = idx
            self._sums[slot] = {}
        bucket = self._sums[slot]
        for k, v in vals.items():
            bucket[k] = bucket.get(k, 0.0) + v

    def sums(self, now: float, since: float | None = None) -> dict[str, float]:
        """Window sums; ``since`` (absolute seconds, same clock as ``add``)
        additionally drops buckets that started at or before it — the
        step controller reads per-tick deltas this way instead of the
        full rolling window, at bucket granularity."""
        idx = int(now / self._width)
        lo = idx - self._buckets + 1
        if since is not None:
            lo = max(lo, int(since / self._width) + 1)
        out: dict[str, float] = {}
        for slot in range(self._buckets):
            if self._epoch[slot] < lo:
                continue
            for k, v in self._sums[slot].items():
                out[k] = out.get(k, 0.0) + v
        return out


class PerfPlane:
    """One engine's live roofline accounting. Thread-safe: the device
    thread notes folded steps, the handoff exporter thread notes
    transfers, and scrape/debug/gossip threads snapshot.

    Device-time semantics: with the pipeline overlapped, per-entry
    dispatch->ready spans double-count the device (entry t's wait covers
    entry t+1's compute). ``note`` therefore clips each step's residency
    to ``t_ready - max(t_dispatch, previous t_ready)`` — consecutive
    steps tile the device timeline exactly, so the window's
    ``device_s`` sum is true busy time. The gap in front of a step
    (``t_dispatch - floor``) is the PIPELINE BUBBLE: the device sat
    idle while this work existed. The engine loop calls
    :meth:`mark_no_work` from its idle branch so genuinely-empty
    periods (no queued work at all) advance the floor instead of
    counting as bubble."""

    def __init__(self, model: CostModel, device_kind: str,
                 *, window_s: float = 60.0, buckets: int = 30):
        self.model = model
        self.device_kind = str(device_kind)
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self._ring = _SumRing(self.window_s, buckets)
        self._gap_floor: float | None = None

    # -- step lifecycle (dispatch side: pure cost arithmetic) ---------------

    def step(self, kind: str, flops: float, bytes_: float,
             t_dispatch: float) -> StepPerf:
        return StepPerf(kind, flops, bytes_, t_dispatch)

    def step_prefill(self, tokens: int, t0: float) -> StepPerf:
        return self.step("prefill", *self.model.prefill(tokens), t0)

    def step_chunk(self, chunk: int, offset: int, t0: float) -> StepPerf:
        return self.step("prefill_chunk", *self.model.chunk(chunk, offset), t0)

    def step_decode(self, lanes: int, k: int, hist_positions: int,
                    t0: float) -> StepPerf:
        return self.step("decode", *self.model.decode(lanes, k, hist_positions), t0)

    def step_spec(self, lanes: int, k: int, g: int, hist_positions: int,
                  t0: float) -> StepPerf:
        return self.step("decode_spec",
                         *self.model.spec(lanes, k, g, hist_positions), t0)

    def step_swapin(self, nbytes: float, t0: float) -> StepPerf:
        return self.step("swapin", *self.model.swapin(nbytes), t0)

    # -- fold side ----------------------------------------------------------

    def note(self, p: StepPerf, now: float, band: str | None = None) -> StepPerf:
        """Account one folded step (engine `_record_step` calls this with
        ``t_ready`` stamped). Returns ``p`` with residency filled.
        ``band`` (an :func:`occupancy_band` label) additionally files the
        step under its band-labeled window — the step controller's
        evidence keys — without touching the kind-level accounting."""
        t_r = p.t_ready if p.t_ready is not None else now
        with self._lock:
            floor = self._gap_floor
            if floor is None:
                floor = p.t_dispatch
            p.bubble_s = max(0.0, p.t_dispatch - floor)
            p.device_s = max(t_r - max(p.t_dispatch, floor), 1e-9)
            p.fold_s = max(0.0, now - t_r)
            self._gap_floor = max(floor, t_r)
            vals = {f"{p.kind}.flops": p.flops,
                    f"{p.kind}.bytes": p.bytes,
                    f"{p.kind}.device_s": p.device_s,
                    f"{p.kind}.steps": 1.0,
                    "bubble_s": p.bubble_s,
                    "busy_s": p.device_s}
            if band is not None:
                # "bd." prefix keeps band rows out of the kind rollups
                # (window_totals filters them the way it filters "ad.")
                bk = f"bd.{p.kind}|{band}"
                vals[f"{bk}.flops"] = p.flops
                vals[f"{bk}.bytes"] = p.bytes
                vals[f"{bk}.device_s"] = p.device_s
                vals[f"{bk}.steps"] = 1.0
                vals[f"{bk}.bubble_s"] = p.bubble_s
            self._ring.add(now, **vals)
        return p

    def note_adapters(self, ids: Iterable[str | None], p: StepPerf,
                      now: float) -> None:
        """Per-adapter attribution of one folded step (multi-LoRA
        multiplexing; gofr_tpu.adapters). ``ids`` carries one entry per
        live lane the fold credited — ``None`` lanes are the base model,
        attributed as ``"base"`` so the per-step adapter shares are a
        COMPLETE partition: summed over adapters they equal the step's
        own flops/bytes/device_s exactly, which is what keeps fleet
        rollups sum-of-parts per tenant (device_s per adapter is the
        per-tenant COGS number). The split is proportional by lane count
        — lanes share the batched step uniformly. Call AFTER :meth:`note`
        (residency must be filled)."""
        ids = list(ids)
        if not ids:
            return
        share = 1.0 / len(ids)
        counts: dict[str, int] = {}
        for aid in ids:
            key = str(aid) if aid is not None else "base"
            counts[key] = counts.get(key, 0) + 1
        with self._lock:
            for aid, c in counts.items():
                f = c * share
                self._ring.add(
                    now,
                    **{f"ad.{aid}.flops": p.flops * f,
                       f"ad.{aid}.bytes": p.bytes * f,
                       f"ad.{aid}.device_s": p.device_s * f,
                       f"ad.{aid}.steps": f})

    def note_external(self, kind: str, device_s: float, flops: float,
                      bytes_: float, now: float) -> None:
        """Account work measured off the device thread (the handoff
        exporter's page readbacks). It rides a different timeline, so it
        contributes flops/bytes/device_s but never moves the ``_dq``
        bubble floor."""
        with self._lock:
            self._ring.add(
                now,
                **{f"{kind}.flops": float(flops),
                   f"{kind}.bytes": float(bytes_),
                   f"{kind}.device_s": max(float(device_s), 1e-9),
                   f"{kind}.steps": 1.0})

    def mark_no_work(self, now: float) -> None:
        """Engine loop idle branch: nothing queued, nothing in flight —
        the gap from here to the next dispatch is idleness, not bubble."""
        with self._lock:
            if self._gap_floor is None or now > self._gap_floor:
                self._gap_floor = now

    # -- read side -----------------------------------------------------------

    def window_totals(self, now: float) -> dict[str, Any]:
        """The mergeable form: per ``kind|kv_dtype`` sums of FLOPs/bytes
        numerators and peak-capacity denominators, plus the bubble sums.
        Capacities are 0.0 when peaks are unknown — a merge then shows
        utilization only for the replicas that know their silicon."""
        peaks = device_peaks(self.device_kind)
        with self._lock:
            sums = self._ring.sums(now)
        kinds: dict[str, dict[str, float]] = {}
        adapters: dict[str, dict[str, float]] = {}
        proto = {"flops": 0.0, "bytes": 0.0, "device_s": 0.0,
                 "steps": 0.0, "flops_cap": 0.0, "bytes_cap": 0.0}
        for key, val in sums.items():
            if key in ("bubble_s", "busy_s"):
                continue
            kind, field = key.rsplit(".", 1)
            if kind.startswith("bd."):
                # band-labeled evidence rows (note(band=)) — read through
                # band_totals by the step controller, never merged here
                continue
            if kind.startswith("ad."):
                # per-adapter attribution rows (note_adapters) — their own
                # section, never mixed into the step kinds
                adapters.setdefault(kind[3:], dict(proto))[field] = val
            else:
                kinds.setdefault(f"{kind}|{self.model.kv_dtype}",
                                 dict(proto))[field] = val
        for rec in list(kinds.values()) + list(adapters.values()):
            if peaks is not None:
                rec["flops_cap"] = rec["device_s"] * peaks[0]
                rec["bytes_cap"] = rec["device_s"] * peaks[1]
        return {
            "v": 1,
            "window_s": self.window_s,
            "kinds": kinds,
            "adapters": adapters,
            "bubble": {"bubble_s": sums.get("bubble_s", 0.0),
                       "busy_s": sums.get("busy_s", 0.0)},
        }

    def band_totals(self, now: float,
                    since: float | None = None) -> dict[str, dict[str, float]]:
        """The step controller's evidence view: per
        ``kind|kv_dtype|band`` sums of FLOPs/bytes/device-seconds/steps
        plus the per-step bubble in front, with capacity denominators
        filled where peaks are known. ``since`` restricts the window to
        buckets after that instant (same clock as ``note``) so ticks read
        deltas, not the rolling window — evidence from before a knob
        move never judges the move."""
        peaks = device_peaks(self.device_kind)
        with self._lock:
            sums = self._ring.sums(now, since)
        out: dict[str, dict[str, float]] = {}
        for key, val in sums.items():
            if not key.startswith("bd."):
                continue
            row, field = key[3:].rsplit(".", 1)
            kind, band = row.split("|", 1)
            rec = out.setdefault(
                f"{kind}|{self.model.kv_dtype}|{band}",
                {"flops": 0.0, "bytes": 0.0, "device_s": 0.0, "steps": 0.0,
                 "bubble_s": 0.0, "flops_cap": 0.0, "bytes_cap": 0.0})
            rec[field] = val
        for rec in out.values():
            if peaks is not None:
                rec["flops_cap"] = rec["device_s"] * peaks[0]
                rec["bytes_cap"] = rec["device_s"] * peaks[1]
        return out

    def snapshot(self, now: float) -> dict[str, Any]:
        """JSON-safe operator view: model constants, resolved peaks, and
        per-kind windowed sums with derived MFU/MBU (None without peaks)."""
        peaks = device_peaks(self.device_kind)
        totals = self.window_totals(now)
        kinds: dict[str, Any] = {}
        for key, rec in totals["kinds"].items():
            kind = key.split("|", 1)[0]
            kinds[kind] = {
                "steps": int(rec["steps"]),
                "flops": rec["flops"],
                "bytes": rec["bytes"],
                "device_s": round(rec["device_s"], 6),
                "mfu": (round(rec["flops"] / rec["flops_cap"], 6)
                        if rec["flops_cap"] else None),
                "mbu": (round(rec["bytes"] / rec["bytes_cap"], 6)
                        if rec["bytes_cap"] else None),
            }
        adapters: dict[str, Any] = {}
        for aid, rec in totals.get("adapters", {}).items():
            adapters[aid] = {
                "steps": round(rec["steps"], 3),
                "flops": rec["flops"],
                "bytes": rec["bytes"],
                "device_s": round(rec["device_s"], 6),
                "mfu": (round(rec["flops"] / rec["flops_cap"], 6)
                        if rec["flops_cap"] else None),
                "mbu": (round(rec["bytes"] / rec["bytes_cap"], 6)
                        if rec["bytes_cap"] else None),
            }
        bub = totals["bubble"]
        denom = bub["bubble_s"] + bub["busy_s"]
        return {
            "device_kind": self.device_kind,
            "kv_dtype": self.model.kv_dtype,
            "window_s": self.window_s,
            "peaks": {
                "flops": peaks[0] if peaks else None,
                "hbm_bytes_per_s": peaks[1] if peaks else None,
                "nominal": bool(peaks) and "cpu" in self.device_kind.lower(),
            },
            "model": self.model.describe(),
            "kinds": kinds,
            "adapters": adapters,
            "bubble": {
                "bubble_s": round(bub["bubble_s"], 6),
                "busy_s": round(bub["busy_s"], 6),
                "ratio": round(bub["bubble_s"] / denom, 6) if denom else None,
            },
        }


# -- exact merges (container / fleet) ----------------------------------------


def merge_totals(parts: Iterable[dict[str, Any] | None]) -> dict[str, Any]:
    """Sum-of-parts merge of :meth:`PerfPlane.window_totals` payloads
    (engines in one container, or replica digests at the router). Sums
    numerators and capacity denominators field by field; NEVER averages
    a ratio — ``merge(merge(a, b), c) == merge(a, b, c)`` exactly."""
    out: dict[str, Any] = {"v": 1, "window_s": 0.0, "kinds": {},
                           "adapters": {},
                           "bubble": {"bubble_s": 0.0, "busy_s": 0.0}}
    for part in parts:
        if not isinstance(part, dict) or "kinds" not in part:
            continue
        out["window_s"] = max(out["window_s"], float(part.get("window_s", 0.0)))
        for section in ("kinds", "adapters"):
            for key, rec in (part.get(section) or {}).items():
                dst = out[section].setdefault(key, {
                    "flops": 0.0, "bytes": 0.0, "device_s": 0.0,
                    "steps": 0.0, "flops_cap": 0.0, "bytes_cap": 0.0})
                for f in dst:
                    dst[f] += float(rec.get(f, 0.0))
        bub = part.get("bubble") or {}
        out["bubble"]["bubble_s"] += float(bub.get("bubble_s", 0.0))
        out["bubble"]["busy_s"] += float(bub.get("busy_s", 0.0))
    return out


def derive(totals: dict[str, Any]) -> dict[str, Any]:
    """Ratios off a (possibly merged) totals payload — computed ONCE,
    at the reporting edge: ``{kind|kv_dtype: mfu/mbu}`` and the bubble
    ratio (None where the denominator is unknown/zero)."""
    mfu: dict[str, float] = {}
    mbu: dict[str, float] = {}
    for key, rec in (totals.get("kinds") or {}).items():
        if rec.get("flops_cap"):
            mfu[key] = rec["flops"] / rec["flops_cap"]
        if rec.get("bytes_cap"):
            mbu[key] = rec["bytes"] / rec["bytes_cap"]
    adapters: dict[str, Any] = {}
    for aid, rec in (totals.get("adapters") or {}).items():
        adapters[aid] = {
            "device_s": float(rec.get("device_s", 0.0)),
            "mfu": (rec["flops"] / rec["flops_cap"]
                    if rec.get("flops_cap") else None),
            "mbu": (rec["bytes"] / rec["bytes_cap"]
                    if rec.get("bytes_cap") else None),
        }
    bub = totals.get("bubble") or {}
    denom = float(bub.get("bubble_s", 0.0)) + float(bub.get("busy_s", 0.0))
    return {
        "mfu": mfu,
        "mbu": mbu,
        "adapters": adapters,
        "bubble_ratio": (float(bub.get("bubble_s", 0.0)) / denom
                         if denom else None),
    }
