"""Headline benchmark: flagship Llama generate throughput through the
continuous-batching engine (BASELINE.md config #2 analog on one chip).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "req/s", "vs_baseline": N}

``vs_baseline`` is value / 125 — the north-star target of ≥1000 req/s on a
v5e-8 (BASELINE.json) prorated to a single chip. The reference publishes
no numbers of its own (BASELINE.md), so the north-star target is the bar.

Backend acquisition is failure-tolerant (round-1 lesson: the 'axon' TPU
plugin can hang at init when the chip tunnel is down, and a hang/traceback
was the round's only artifact). We probe TPU init in a SUBPROCESS with a
timeout, retry once, and on failure pin the CPU backend and run a scaled
preset — the JSON line always appears, with the platform reported honestly.

A fallback the operator did not ask for is additionally a LOUD failure
(ISSUE 11, ROADMAP O3: BENCH_r04/r05 archived CPU numbers as "green"):
``vs_baseline`` becomes the string ``INVALID_CPU_FALLBACK`` and the process
exits 3 after printing, so a harness can never archive a silent CPU run as
a TPU datapoint. ``GOFR_BENCH_PLATFORM=cpu`` (explicit) and
``GOFR_BENCH_ALLOW_CPU=1`` (CI smokes) remain valid, clearly-labelled CPU
runs with exit 0.

Env knobs:
    GOFR_BENCH_PRESET         one_b (default on TPU) | eight_b (Llama-3-8B shape,
                              the north-star model class) | tiny (CPU fallback default)
    GOFR_BENCH_REQUESTS       total requests (default 512 TPU / 8 CPU)
    GOFR_BENCH_SLOTS          decode slots (default 128 TPU / 16 CPU)
    GOFR_BENCH_CHUNK          decode chunk (default 32 TPU / 8 CPU)
    GOFR_BENCH_PREFILL_BATCH  max prompts per prefill call (default 128 TPU / 4 CPU)
    GOFR_BENCH_QUANTIZE       'int8' (TPU default) | '' = bf16
    GOFR_BENCH_PROMPT         prompt length (default 64)
    GOFR_BENCH_NEW            generated tokens per request (default 64)
    GOFR_BENCH_PLATFORM       force 'cpu' or 'tpu' (skips the probe)
    GOFR_BENCH_PROBE_S        TPU init probe timeout seconds (default 240)
    GOFR_BENCH_KV             'slot' (default) | 'paged' engine KV layout
    GOFR_BENCH_KV_QUANTIZE    'int8' = int8 KV cache (slot and paged layouts);
                              'int4' = packed-int4 KV pages (paged only, ISSUE 13)
    GOFR_BENCH_KVDTYPE        1 = also run the paged-pool dtype three-way A/B
                              (bf16 / int8 / int4 arms): req/s, decode TPOT
                              p50/p99, exact pool bytes-per-decode-token,
                              per-arm mbu_decode_lb, and token_exact/parity
                              vs the bf16 arm land in extra.kvdtype
    GOFR_BENCH_TP             1 = also run the tensor-parallel paged-pool A/B
                              (ISSUE 19): replicated vs tp-sharded KV pool
                              on a forced multi-device host mesh (export
                              XLA_FLAGS=--xla_force_host_platform_device_
                              count=8), asserting token-exactness vs the
                              single-device greedy reference, per-device
                              pool bytes ≈ 1/tp, and strictly more pool
                              pages at equal per-device HBM budget; verdicts
                              land in extra.tp
    GOFR_BENCH_TP_MESH        mesh for the TP A/B (default "dp:2,tp:4")
    GOFR_BENCH_SPEC           N>0 = speculative decoding with N lookup drafts
    GOFR_BENCH_SPEC_AB        1 = also measure paced mixed arrivals with spec
                              rounds on vs off at the configured KV layout
                              (extra.spec_ab — the ISSUE 13 evidence that
                              paged spec rides the async pipeline instead of
                              serializing the device loop)
    GOFR_BENCH_PREFIX         1 = also measure the forced-spill shared-prefix
                              workload on the paged engine, three-way: cache
                              off / HBM-only / HBM+host spill tier (cold and
                              warm TTFT p50, per-tier hit tokens)
    GOFR_BENCH_ROUTER         1 = also measure the multi-replica router A/B
                              (gofr_tpu.router): 2 in-process replicas under
                              a tenant-skewed shared-prefix workload, prefix-
                              affinity vs random routing (aggregate req/s,
                              warm-TTFT p50, prefix hit-token ratio per arm)
    GOFR_BENCH_SLO            1 = also run the heavy-tailed SLO workload
                              (lognormal prompt/output lengths, bursty
                              arrivals, zipf tenant skew mapped onto QoS
                              classes) and report per-class SLO attainment
                              + burn-rate peaks from metrics/slo.py in
                              extra.slo (ROADMAP O5(b))
    GOFR_BENCH_STORM          1 = also run the cancel/retry-storm drill
                              (ISSUE 10, ROADMAP O5(b)): doomed-deadline
                              submissions must shed pre-slot with
                              deadline_exceeded, chaos-scheduled client
                              disconnects mid-decode must leak zero
                              slots/pages (assert_page_refs_consistent
                              after drain), and a synthetic 5xx retry
                              storm through the shared RetryBudget must
                              keep amplification <= the budget fraction;
                              results in extra.storm
    GOFR_BENCH_DIURNAL        1 = also run the trace-driven diurnal
                              elasticity harness (ISSUE 11, ROADMAP O2): a
                              24h-compressed sinusoidal arrival curve with
                              burst hours and zipf tenant skew, replayed
                              against a static max-replica fleet AND an
                              elastic fleet driven by fleet/autoscaler.py;
                              per-class SLO attainment and chip-seconds-
                              per-request for both arms land in
                              extra.autoscale
    GOFR_BENCH_DIURNAL_S      compressed trace duration seconds (default 60)
    GOFR_BENCH_DIURNAL_REQUESTS  trace size (default max(24, 3x requests))
    GOFR_BENCH_DIURNAL_MAX    replica clamp for both arms (default 3)
    GOFR_BENCH_DIURNAL_SLOTS  decode slots per replica (default min(4, slots))
    GOFR_BENCH_DISAGG         1 = also run the disaggregated prefill/decode
                              A/B (ISSUE 12): resident decode streams are
                              measured quiet and then under a concurrent
                              prefill wave, once colocated (ENGINE_ROLE=
                              both) and once role-split (prefill worker →
                              paged-KV handoff over loopback TCP → decode
                              worker); TTFT/TPOT percentiles, the TPOT-p99
                              degradation ratio per arm, token-exactness
                              across arms and the handoff transfer stats
                              land in extra.disagg
    GOFR_BENCH_DISAGG_RESIDENTS  resident decode streams per phase (default 4)
    GOFR_BENCH_DISAGG_WAVE    concurrent prefill-wave size (default
                              max(4, requests/2))
    GOFR_BENCH_QUALITY        1 = also run the numerics quality-plane drill
                              (ISSUE 17): clean arms at bf16/int8/int4 paged
                              KV with the divergence shadow at rate 1.0 must
                              score every request against the dense-bf16
                              reference with zero quality-SLO breaches, and
                              a chaos-corrupted int8 arm (quality.corrupt
                              scale perturbation) must drop top1 agreement,
                              fire the quality burn, write an enriched
                              capture bundle, and reproduce offline via
                              scripts/replay_bundle.py; per-arm agreement
                              stats + the chaos verdict land in extra.quality
    GOFR_BENCH_ADAPTERS       1 = also run the multi-LoRA consolidation A/B:
                              N adapters multiplexed on ONE engine vs N
                              dedicated single-adapter engines, same seeded
                              workload — archives chip-seconds/request at
                              equal attainment and per-arm token-exactness
    GOFR_BENCH_ADAPTERS_N     adapter count for the A/B (default 3)
    GOFR_BENCH_CONTROLLER     1 = also run the online step-controller A/B
                              (gofr_tpu.control): a three-phase shifting
                              workload (burst → paced → trickle) replayed
                              against EVERY static knob setting inside the
                              boot envelope (pipeline depth × prefill
                              batch) and against a controller-driven engine
                              deliberately started at the pessimal setting
                              so it must climb; per-arm attainment, bubble
                              ratio and score (attainment × (1 − bubble))
                              land in extra.controller, with the decision
                              count, the final knob vector, token-exactness
                              across all arms (knob moves are token-
                              neutral by contract) and the meets_statics
                              verdict
    GOFR_BENCH_CONTROLLER_TOL relative score slack for meets_statics
                              (default 0.25 — the CPU smoke's noise floor)
    GOFR_BENCH_CONTROLLER_INTERVAL_S  controller tick seconds for the
                              smoke (default 0.3)
    GOFR_BENCH_CONTROLLER_SPAN_S  wall-clock span the paced + trickle
                              phases stretch over (default 8 — room for
                              ~span/interval controller evidence windows)
    GOFR_BENCH_ALLOW_CPU      1 = a TPU-probe CPU fallback stays a valid
                              (labelled) CPU run instead of failing loud
    GOFR_BENCH_PIPELINE       device pipeline depth (default 2; 1 = sync, up to 4)
    GOFR_BENCH_OVERLAP_AB     1 = also measure the mixed-arrival workload (paced
                              arrivals of short + chunked-long prompts) with the
                              unified async pipeline on (depth>=2) vs off (1),
                              recording req/s and TTFT for each
    GOFR_BENCH_ARRIVAL_MS     mixed-arrival inter-arrival gap in ms (default
                              adaptive: headline elapsed / requests / 2)
    GOFR_BENCH_LATENCY        1 = also measure sequential single-request latency
    GOFR_BENCH_SWEEP          1 = sweep slots x decode_chunk, keep best
    GOFR_BENCH_PALLAS_AB      1 = record kernel-on/off engine A/B
    GOFR_BENCH_DEBUG          1 = per-phase device-call accounting in extra
    GOFR_TPU_PEAK_TFLOPS      override bf16 peak for MFU (default by device kind)
    GOFR_TPU_PEAK_GBS         override HBM GB/s for MBU (default by device kind)
    GOFR_AUTOTUNE             0 = disable the warmup kernel autotuner; the
                              decision table lands in extra.autotune either way
    GOFR_AUTOTUNE_CACHE       path for autotune decisions (restarts skip re-timing)

The JSON line also reports extra.mbu_decode_lb against the newest archived
BENCH_r*.json round (extra.mbu_prev: round, value, delta) so kernel wins
and regressions are visible per PR without diffing artifacts.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

_PROBE_SRC = "import jax; d = jax.devices(); print('PLATFORM=' + d[0].platform + ',KIND=' + d[0].device_kind)"


def _pin_cpu() -> None:
    from jaxpin import pin_cpu

    pin_cpu(1)


def _probe_tpu(timeout_s: float) -> tuple[bool, str]:
    """Initialize the default (TPU) backend in a subprocess so a hung or
    failing init can't take this process down. Returns (ok, detail)."""
    from jaxpin import child_env

    try:
        out = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True, text=True, timeout=timeout_s,
            env=child_env(),  # inherited JAX_PLATFORMS would block sitecustomize
        )
    except subprocess.TimeoutExpired:
        return False, f"probe timed out after {timeout_s:.0f}s"
    if out.returncode != 0:
        tail = (out.stderr or out.stdout).strip().splitlines()[-1:] or ["no output"]
        return False, f"probe rc={out.returncode}: {tail[0][:200]}"
    marker = [ln for ln in out.stdout.splitlines() if ln.startswith("PLATFORM=")]
    if not marker:
        return False, "probe produced no platform marker"
    detail = marker[0]
    if "PLATFORM=cpu" in detail:
        return False, f"default backend is cpu ({detail})"
    return True, detail


def acquire_backend() -> tuple[str, str]:
    """→ (platform, diagnostic). Never hangs, never raises."""
    forced = os.environ.get("GOFR_BENCH_PLATFORM")
    if forced == "cpu":
        _pin_cpu()
        return "cpu", "forced by GOFR_BENCH_PLATFORM"
    probe_s = float(os.environ.get("GOFR_BENCH_PROBE_S", "240"))
    if forced == "tpu":
        return "tpu", "forced by GOFR_BENCH_PLATFORM (no probe)"
    detail = ""
    # A hung tunnel is rarely transient: the retry probe gets a short budget
    # so worst-case stall is probe_s + 60s, not 2x probe_s (round-1 rc=124
    # was an outer-timeout kill while waiting on exactly this kind of hang).
    for attempt, budget in ((1, probe_s), (2, min(60.0, probe_s))):
        ok, detail = _probe_tpu(budget)
        if ok:
            return "tpu", f"attempt {attempt}: {detail}"
        if "default backend is cpu" in detail:
            break  # deterministic: no TPU plugin here, retry is wasted startup
    _pin_cpu()
    return "cpu", f"TPU unavailable, CPU fallback ({detail})"


def _device_peaks(device) -> tuple[float, float] | None:
    """(peak FLOPs/s, peak HBM bytes/s) for the bench device, resolved by
    the SAME table/override chain the live engine perf plane uses
    (metrics/perf.py): GOFR_TPU_PEAK_* > GOFR_DEVICE_PEAKS JSON > builtin
    spec sheet. One source of truth — bench and serving can't disagree."""
    from gofr_tpu.metrics import perf as perf_mod

    kind = (getattr(device, "device_kind", "") or
            getattr(device, "platform", "") or "")
    return perf_mod.device_peaks(str(kind))


def _peak_flops(device) -> float:
    """bf16 peak for MFU; assume v5e-class when unknown."""
    peaks = _device_peaks(device)
    return peaks[0] if peaks else 197e12


def _peak_bw(device) -> float:
    """HBM bandwidth for MBU — decode is bandwidth-bound, so MBU (not MFU)
    is the utilization that matters for the generate bench; assume
    v5e-class when unknown."""
    peaks = _device_peaks(device)
    return peaks[1] if peaks else 819e9


def _pallas_active() -> bool:
    """The single source of truth for whether 'auto' resolves to kernels."""
    from gofr_tpu.ops.pallas import flash_attention_available

    return flash_attention_available()


def _prev_bench_extra() -> tuple[int, dict] | None:
    """(round, extra) from the newest prior BENCH_r*.json next to this file.

    Bench rounds archive the run as {"n", "cmd", "rc", "tail", "parsed"};
    prefer the structured "parsed" record, falling back to scanning the
    (possibly truncated) output tail for the metric line. Used to report
    the mbu_decode_lb / autotune-decision delta per PR (ROADMAP O3: kernel
    wins and regressions must be visible per round)."""
    import glob
    import re

    here = os.path.dirname(os.path.abspath(__file__))
    rounds = []
    for p in glob.glob(os.path.join(here, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        if m:
            rounds.append((int(m.group(1)), p))
    for n, p in sorted(rounds, reverse=True):
        try:
            with open(p, encoding="utf-8") as f:
                doc = json.load(f)
        except Exception:  # noqa: BLE001 - a torn archive is just skipped
            continue
        if not isinstance(doc, dict):
            continue
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and isinstance(parsed.get("extra"), dict):
            return n, parsed["extra"]
        for line in reversed(str(doc.get("tail", "")).splitlines()):
            line = line.strip()
            if line.startswith("{") and '"metric"' in line:
                try:
                    rec = json.loads(line)
                except Exception:  # noqa: BLE001
                    continue
                if isinstance(rec, dict):
                    return n, rec.get("extra") or {}
    return None


def _percentile(xs: list[float], p: float) -> float:
    ys = sorted(xs)
    idx = min(len(ys) - 1, max(0, int(round(p / 100.0 * (len(ys) - 1)))))
    return ys[idx]


def _run_once(engine_kw: dict, cfg, params, container, family, prompts,
              max_new: int, timeout: float) -> dict:
    """Serve all prompts through a fresh engine; return raw measurements."""
    import numpy as np

    from gofr_tpu.tpu.engine import GenerateEngine

    engine = GenerateEngine(family, cfg, params, container, **engine_kw)
    try:
        # compile every serving signature outside the timed window — a 3s
        # tunnel compile inside it would swamp an 11s measurement
        engine.warmup()
        engine.start()
        engine.generate(prompts[0], max_new_tokens=2, timeout=timeout)

        results: list[dict | None] = [None] * len(prompts)
        errors: list[Exception] = []

        # futures submission (engine.submit): all requests in flight from one
        # thread — the shape the asyncio transports use, and it keeps N
        # client threads from fighting the device thread for the GIL
        t0 = time.monotonic()
        reqs = [engine.submit(p, max_new_tokens=max_new, timeout=timeout) for p in prompts]
        for i, r in enumerate(reqs):
            try:
                results[i] = r.result(timeout)
            except Exception as e:  # noqa: BLE001
                errors.append(e)
        elapsed = time.monotonic() - t0
        # live perf plane (metrics/perf.py): the per-kind roofline the run
        # actually measured, snapshotted before stop() tears the engine down
        perf_snap = (engine.perf.snapshot(time.monotonic())
                     if getattr(engine, "perf", None) is not None else None)
    finally:
        engine.stop()

    if errors or any(r is None for r in results):
        raise RuntimeError(f"bench requests failed: {errors[:1]} "
                           f"({sum(r is None for r in results)} incomplete)")
    new_tokens = int(np.sum([len(r["tokens"]) for r in results]))
    out = {
        "elapsed": elapsed,
        "new_tokens": new_tokens,
        "ttfts": [r["ttft_s"] for r in results],
        "perf": perf_snap,
    }
    if os.environ.get("GOFR_BENCH_DEBUG") == "1":
        # device-call accounting from the engine's own histograms: how much
        # of the wall clock the device steps explain vs host/RTT overhead
        steps = engine.metrics.get("app_tpu_step_seconds")
        if steps is not None:
            phases = {}
            for kind in ("prefill", "prefill_chunk", "decode"):
                calls = steps.count(kind=kind)
                if calls:
                    phases[kind] = {"calls": calls, "seconds": round(steps.sum(kind=kind), 3)}
            out["phases"] = phases
            out["device_seconds"] = round(sum(p["seconds"] for p in phases.values()), 3)
    return out


def _run_mixed(engine_kw: dict, cfg, params, container, family, prompts,
               max_new: int, timeout: float, arrival_s: float) -> dict:
    """Serve ``prompts`` with PACED arrivals (one submit per ``arrival_s``,
    not an up-front burst): the workload where synchronous prefill stalls
    every decoding slot for a full device round trip per arrival, and the
    unified async pipeline keeps them stepping. Returns raw measurements."""
    from gofr_tpu.tpu.engine import GenerateEngine

    engine = GenerateEngine(family, cfg, params, container, **engine_kw)
    try:
        engine.warmup()
        engine.start()
        engine.generate(prompts[-1], max_new_tokens=2, timeout=timeout)

        t0 = time.monotonic()
        reqs = []
        for i, p in enumerate(prompts):
            target = t0 + i * arrival_s
            delay = target - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            reqs.append(engine.submit(p, max_new_tokens=max_new, timeout=timeout))
        results = [r.result(timeout) for r in reqs]
        elapsed = time.monotonic() - t0
    finally:
        engine.stop()
    return {
        "elapsed": elapsed,
        "new_tokens": sum(len(r["tokens"]) for r in results),
        "ttfts": [r["ttft_s"] for r in results],
    }


def main() -> None:
    platform, backend_diag = acquire_backend()

    import jax
    import numpy as np

    # Persistent compile cache: sweep points and repeat runs re-use compiled
    # programs across processes instead of paying ~3s/signature each time.
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/gofr_jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # noqa: BLE001 - older jax; cache is an optimization only
        pass

    from gofr_tpu.container import new_mock_container
    from gofr_tpu.models import LlamaConfig, llama

    on_cpu = platform == "cpu"
    preset = os.environ.get("GOFR_BENCH_PRESET", "tiny" if on_cpu else "one_b")
    n_requests = int(os.environ.get("GOFR_BENCH_REQUESTS", "8" if on_cpu else "512"))
    # Round-3 TPU lesson (diag: 100ms tunnel RTT per host sync, ~3ms/step
    # device compute): throughput is won by amortizing round trips — large
    # decode chunks, wide prefill batches, many slots. Defaults are the
    # measured round-3 grid winner (143.7 req/s, vs_baseline 1.15).
    slots = int(os.environ.get("GOFR_BENCH_SLOTS", "16" if on_cpu else "128"))
    decode_chunk = int(os.environ.get("GOFR_BENCH_CHUNK", "8" if on_cpu else "32"))
    prefill_batch = int(os.environ.get("GOFR_BENCH_PREFILL_BATCH", "4" if on_cpu else "128"))
    prompt_len = int(os.environ.get("GOFR_BENCH_PROMPT", "64"))
    max_new = int(os.environ.get("GOFR_BENCH_NEW", "16" if on_cpu else "64"))
    timeout = 600.0 if on_cpu else 1200.0

    presets = {"tiny": LlamaConfig.tiny, "one_b": LlamaConfig.one_b,
               "eight_b": LlamaConfig.llama3_8b}
    if preset not in presets:
        raise SystemExit(f"GOFR_BENCH_PRESET={preset!r}: use {sorted(presets)}")
    cfg = presets[preset]()

    container = new_mock_container()
    params = llama.init(cfg, jax.random.key(0))
    n_params = sum(int(x.size) for x in jax.tree.leaves(params))

    # weight-only int8 (ops/quant.py): halves the per-step weight reads
    # decode is bound by — measured 1.33x decode throughput on v5e. Default
    # on for the TPU headline (it's a standard serving configuration);
    # GOFR_BENCH_QUANTIZE= (empty) benches bf16.
    quantize = os.environ.get("GOFR_BENCH_QUANTIZE", "" if on_cpu else "int8")
    if quantize == "int8":
        from gofr_tpu.ops.quant import quantize_tree

        params = jax.jit(quantize_tree)(params)
    elif quantize:
        # a typo'd mode must not silently bench bf16 while REPORTING the typo
        raise SystemExit(f"GOFR_BENCH_QUANTIZE={quantize!r}: only 'int8' (or empty) is supported")
    from gofr_tpu.ops.quant import quantized_bytes

    param_bytes = float(quantized_bytes(params))

    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, cfg.vocab_size, size=prompt_len).tolist() for _ in range(n_requests)]

    kv_layout = os.environ.get("GOFR_BENCH_KV", "slot")
    if kv_layout not in ("slot", "paged"):
        # a typo'd layout must not silently bench slot while REPORTING the typo
        raise SystemExit(f"GOFR_BENCH_KV={kv_layout!r}: use 'slot' or 'paged'")

    # unified device pipeline (engine default 2): call t+1 — decode chunk OR
    # prefill — is dispatched before call t is read back, hiding the per-step
    # readback RTT. 1 = synchronous. Validate here: the engine clamps
    # silently, and the report must never state a depth that was not actually
    # benched (same rule as GOFR_BENCH_KV).
    pipeline_env = os.environ.get("GOFR_BENCH_PIPELINE", "2")
    if pipeline_env not in ("1", "2", "3", "4"):
        raise SystemExit(f"GOFR_BENCH_PIPELINE={pipeline_env!r}: use 1 (sync) .. 4")
    pipeline = int(pipeline_env)

    kv_quantize = os.environ.get("GOFR_BENCH_KV_QUANTIZE", "")
    if kv_quantize not in ("", "int8", "int4"):
        raise SystemExit(
            f"GOFR_BENCH_KV_QUANTIZE={kv_quantize!r}: only 'int8' or 'int4' (or empty)")
    if kv_quantize == "int4" and kv_layout != "paged":
        # same fail-loud rule: int4 KV is packed-nibble PAGES (ISSUE 13);
        # silently benching the slot layout would report the wrong config
        raise SystemExit("GOFR_BENCH_KV_QUANTIZE=int4 needs GOFR_BENCH_KV=paged")
    spec_tokens = int(os.environ.get("GOFR_BENCH_SPEC", "0"))

    def engine_kw(s: int, k: int) -> dict:
        kw = dict(slots=s, max_len=prompt_len + max_new + 8,
                  max_prefill_batch=prefill_batch, decode_chunk=k,
                  prefill_buckets=[prompt_len], decode_pipeline=pipeline)
        if kv_layout == "paged":
            kw.update(kv_layout="paged", page_size=128)
        if spec_tokens:
            kw.update(spec_tokens=spec_tokens)
        if kv_quantize:
            kw.update(kv_quantize=kv_quantize)
        return kw

    best = (slots, decode_chunk)
    sweep_log = []
    if os.environ.get("GOFR_BENCH_SWEEP") == "1":
        short = prompts[: max(4, n_requests // 4)]
        best_rate = 0.0
        # grid seeded with the operator's env-configured point so an explicit
        # GOFR_BENCH_SLOTS/CHUNK is always measured, never silently dropped.
        # TPU grid targets RTT amortization (big chunks/slot counts); the CPU
        # grid stays small so the fallback bench finishes quickly.
        if on_cpu:
            grid = sorted({(s, k) for s in (8, 16, 32) for k in (4, 8, 16)} | {best})
        else:
            grid = sorted({(s, k) for s in (16, 32, 64) for k in (8, 32, 64)} | {best})
        for s, k in grid:
            try:
                m = _run_once(engine_kw(s, k), cfg, params, container, llama,
                              short, max_new, timeout)
            except Exception as e:  # noqa: BLE001
                sweep_log.append({"slots": s, "chunk": k, "error": str(e)[:120]})
                continue
            rate = len(short) / m["elapsed"]
            sweep_log.append({"slots": s, "chunk": k, "req_per_s": round(rate, 3)})
            if rate > best_rate:
                best_rate, best = rate, (s, k)

    # Variant auto-selection (TPU default; GOFR_BENCH_AUTO=0 disables):
    # short A/B of the int8 KV cache, keeping the winner for the headline.
    # Valid IN-process unlike the GOFR_*_KV_WRITE lowerings: the quantized
    # cache is a different pytree type, so jit traces a fresh program.
    if (os.environ.get("GOFR_BENCH_AUTO", "0" if on_cpu else "1") == "1"
            and not kv_quantize and not spec_tokens):
        short = prompts[: max(8, n_requests // 8)]
        ab_rates: dict = {}
        for name, kwv in (("base", {}), ("kv8", {"kv_quantize": "int8"})):
            try:
                mv = _run_once({**engine_kw(*best), **kwv}, cfg, params, container,
                               llama, short, max_new, timeout)
                ab_rates[name] = round(len(short) / mv["elapsed"], 2)
            except Exception as e:  # noqa: BLE001
                ab_rates[name] = f"error: {e}"[:120]
        # Require a >3% margin to switch the headline config so single short-
        # sample noise can't flip it between rounds (numbers stay comparable);
        # the raw A/B rates are always recorded in extra either way.
        if (isinstance(ab_rates.get("kv8"), float)
                and isinstance(ab_rates.get("base"), float)
                and ab_rates["kv8"] > ab_rates["base"] * 1.03):
            kv_quantize = "int8"
    else:
        ab_rates = {}

    def _counter_total(cont, name) -> float:
        mm = cont.metrics.get(name)
        return sum(mm._values.values()) if mm is not None else 0.0

    spec_acc0 = _counter_total(container, "app_tpu_spec_accepted")
    spec_prop0 = _counter_total(container, "app_tpu_spec_proposed")
    try:
        m = _run_once(engine_kw(*best), cfg, params, container, llama,
                      prompts, max_new, timeout)
    except Exception as e:  # noqa: BLE001
        print(json.dumps({"metric": "bench_error", "value": 0, "unit": "req/s",
                          "vs_baseline": 0, "error": str(e)[:400],
                          "extra": {"platform": platform, "backend": backend_diag}}))
        sys.exit(1)

    elapsed = m["elapsed"]
    req_per_s = n_requests / elapsed
    tok_per_s = m["new_tokens"] / elapsed

    # MFU: decode costs ~2*N FLOPs/token, prefill ~2*N per prompt token
    # (attention FLOPs are <2% at these lengths; ignored — conservative).
    # NB: the image's TPU plugin registers as platform 'axon', not 'tpu' —
    # gate accelerator-only reporting on != 'cpu', same as the probe.
    # Utilization is reported whenever the peak table resolves — on CPU
    # that is the NOMINAL envelope (metrics/perf.py), flagged below so a
    # CPU number is never mistaken for silicon utilization.
    from gofr_tpu.metrics import perf as _perf
    from gofr_tpu.ops.paged import kv_plane_bytes_per_position

    device = jax.devices()[0]
    on_accel = device.platform != "cpu"
    peaks = _device_peaks(device)
    total_flops = 2.0 * n_params * (m["new_tokens"] + n_requests * prompt_len)
    mfu = total_flops / elapsed / peaks[0] if peaks else None
    # decode-side MBU lower bound via the SHARED estimator (perf.
    # decode_lb_bytes): weight re-reads per micro-step PLUS the KV-pool
    # traffic at the active plane width — the pre-perf-plane weights-only
    # formula undercounted every byte the cache streams. kv_bytes_per_pos
    # comes from the engine's own perf plane (exact pool footprint) with
    # the analytic plane-width formula as the engine-less fallback; the
    # old bound is kept as mbu_decode_lb_params for trajectory continuity.
    eng_model = (m.get("perf") or {}).get("model") or {}
    kv_bytes_pos = float(eng_model.get("kv_bytes_per_pos") or 0.0)
    if not kv_bytes_pos:
        kv_bytes_pos = kv_plane_bytes_per_position(
            cfg.num_layers, cfg.num_kv_heads, cfg.head_size,
            kv_dtype=kv_quantize or "bf16",
            dense_bytes=4 if on_cpu else 2)
    lb_inputs = {
        "weight_bytes": float(param_bytes),
        "new_tokens": int(m["new_tokens"]),
        "slots": int(best[0]),
        "kv_bytes_per_pos": float(kv_bytes_pos),
        "hist_len": int(prompt_len),
    }
    mbu = (_perf.mbu_decode_lb(**lb_inputs, elapsed_s=elapsed, peak_bw=peaks[1])
           if peaks else None)
    mbu_params = (_perf.mbu_decode_lb_params(
        weight_bytes=float(param_bytes), new_tokens=int(m["new_tokens"]),
        slots=int(best[0]), elapsed_s=elapsed, peak_bw=peaks[1])
        if peaks else None)

    extra = {
        "decode_tokens_per_s": round(tok_per_s, 1),
        "requests": n_requests,
        "prompt_len": prompt_len,
        "max_new_tokens": max_new,
        "slots": best[0],
        "decode_chunk": best[1],
        "decode_pipeline": pipeline,
        "platform": device.platform,
        "device_kind": getattr(device, "device_kind", "?"),
        "backend": backend_diag,
        "elapsed_s": round(elapsed, 2),
        "n_params": n_params,
        "quantize": quantize or "bf16",
        "param_bytes": int(param_bytes),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "mbu_decode_lb": round(mbu, 4) if mbu is not None else None,
        "mbu_decode_lb_params": (round(mbu_params, 4)
                                 if mbu_params is not None else None),
        "peaks_nominal": bool(peaks) and not on_accel,
        "ttft_p50_s": round(_percentile(m["ttfts"], 50), 4),
        "ttft_p99_s": round(_percentile(m["ttfts"], 99), 4),
    }
    # the per-kind roofline breakdown the headline engine measured, plus
    # the EXACT estimator inputs: CI recomputes mbu_decode_lb from these
    # via the shared module and asserts bit-for-bit agreement.
    extra["perf"] = {
        "inputs": dict(lb_inputs, elapsed_s=elapsed,
                       peak_bw=peaks[1] if peaks else None),
        "engine": m.get("perf"),
    }
    # warmup autotuner decision table (ops/autotune.py): which backend each
    # decode op pinned for this run's engine, with the measured timings —
    # the per-PR record ROADMAP O3 asks for. The headline engine is the
    # last to warm up before this point, so the module-level report is its.
    from gofr_tpu.ops import autotune as _autotune

    at_rep = _autotune.last_report()
    extra["autotune"] = at_rep or {"enabled": _autotune.enabled(), "decisions": {}}
    # kernel status derives from what actually served the run: the autotune
    # pins when the tuner decided, else the static GOFR_PALLAS gate (the
    # pre-autotuner posture — see docs/kernels.md for the precedence chain)
    if at_rep and at_rep.get("decisions"):
        extra["pallas"] = "autotuned: " + ", ".join(
            f"{op}->{rec.get('backend')}"
            for op, rec in sorted(at_rep["decisions"].items()))
    else:
        extra["pallas"] = ("on (GOFR_PALLAS static gate)" if _pallas_active()
                           else "off (static gate; see docs/kernels.md)")
    # regression tracking: delta vs the newest archived round so a kernel
    # win (or loss) is visible in every round's artifact without diffing
    prev = _prev_bench_extra()
    if prev is not None:
        prev_round, prev_extra = prev
        prev_mbu = prev_extra.get("mbu_decode_lb")
        extra["mbu_prev"] = {"round": prev_round, "mbu_decode_lb": prev_mbu}
        cur_mbu = extra["mbu_decode_lb"]
        if cur_mbu is not None and isinstance(prev_mbu, (int, float)):
            extra["mbu_prev"]["delta"] = round(cur_mbu - prev_mbu, 4)
        print(
            f"mbu_decode_lb: {cur_mbu} (prev round r{prev_round:02d}: "
            f"{prev_mbu}, delta "
            f"{extra['mbu_prev'].get('delta', 'n/a')}); autotune: "
            + (", ".join(
                f"{op}->{rec.get('backend')}" for op, rec in
                (extra["autotune"].get("decisions") or {}).items()) or "none"),
            file=sys.stderr)
    if kv_layout != "slot":
        extra["kv_layout"] = kv_layout
    if kv_quantize:
        extra["kv_quantize"] = kv_quantize
    if ab_rates:
        extra["kv8_ab_req_per_s"] = ab_rates
    if spec_tokens:
        extra["spec_tokens"] = spec_tokens
        # delta vs the pre-headline snapshot: sweep/warmup runs share the
        # process-wide container counters and must not pollute the ratio
        acc_d = _counter_total(container, "app_tpu_spec_accepted") - spec_acc0
        prop_d = _counter_total(container, "app_tpu_spec_proposed") - spec_prop0
        if prop_d:
            extra["spec_acceptance"] = round(acc_d / prop_d, 3)
    if "phases" in m:
        extra["phases"] = m["phases"]
        extra["device_seconds"] = m["device_seconds"]

    # latency mode: STRICTLY sequential single requests — the occupancy-1
    # counterpoint to the throughput headline (the full-slots decode program
    # runs for one lane, so this bounds per-request interactive latency)
    if os.environ.get("GOFR_BENCH_LATENCY") == "1":
        from gofr_tpu.tpu.engine import GenerateEngine

        # a latency-pass failure must not lose the already-measured headline
        try:
            eng = GenerateEngine(llama, cfg, params, container, **engine_kw(*best))
            try:
                eng.warmup()
                eng.start()
                eng.generate(prompts[0], max_new_tokens=2, timeout=timeout)
                t0 = time.monotonic()
                for i in range(4):
                    eng.generate(prompts[i % len(prompts)], max_new_tokens=max_new, timeout=timeout)
                per_req = (time.monotonic() - t0) / 4
            finally:
                eng.stop()
            extra["single_request_s"] = round(per_req, 3)
            # end-to-end rate (prefill included) — NOT comparable to the
            # decode-only headline rate
            extra["single_request_tok_s"] = round(max_new / per_req, 1)
        except Exception as e:  # noqa: BLE001
            extra["single_request_error"] = str(e)[:200]
    if sweep_log:
        extra["sweep"] = sweep_log

    # shared-prefix workload on the paged engine, THREE-way A/B (ISSUE 4):
    # cache off / HBM-only / HBM + host-DRAM spill tier. Several groups of
    # prompts each share a 2-page prefix; the page pool is sized so the
    # groups cannot all stay cached in HBM — mid-run pool pressure evicts
    # (HBM-only) or spills to host (HBM+host) the colder groups' pages.
    # Each arm runs one concurrent COLD wave over every prompt (throughput
    # + cold TTFT), then sequential WARM PROBES re-issuing one prompt for
    # each of the oldest groups — the HBM-only arm must re-prefill their
    # evicted prefixes while the host arm swaps them back in over the
    # device pipeline, which is exactly the warm-TTFT gap reported.
    if os.environ.get("GOFR_BENCH_PREFIX") == "1":
        from gofr_tpu.tpu.engine import GenerateEngine

        def _tier_totals(name) -> dict:
            mm = container.metrics.get(name)
            out: dict = {}
            if mm is not None:
                for ls, v in mm._values.items():
                    tier = dict(ls).get("tier", "")
                    out[tier] = out.get(tier, 0.0) + v
            return out

        groups = 6
        n_per = max(2, n_requests // 32)
        # a LONG shared prefix (several pages) + a half-page unique tail per
        # prompt: re-prefilling the prefix costs real compute while a host
        # swap-in is one upload, so the tiers separate even on the CPU
        # fallback; scaled down for tiny configs
        ppage = 128 if cfg.max_seq_len >= 512 else 16
        shared_pages = 6
        tail = ppage // 2
        shared = [rng.randint(1, cfg.vocab_size, size=shared_pages * ppage).tolist()
                  for _ in range(groups)]
        pprompts = [s + rng.randint(1, cfg.vocab_size, size=tail).tolist()
                    for s in shared for _ in range(n_per)]
        pref_new = min(max_new, 8)  # decode is not what this A/B measures
        p_slots = max(2, min(best[0], 4))
        p_max_len = shared_pages * ppage + tail + pref_new + 8
        pages_per_slot = -(-(p_max_len + best[1]) // ppage)
        # pool covers the active slots plus ONE group's prefix of spare:
        # the cached corpus (groups * shared_pages) cannot stay resident, so
        # pressure comes from cache RETENTION, not slot demand — the forced-
        # spill condition the A/B exists to measure, without allocation
        # thrash between concurrent slots
        p_pages = p_slots * pages_per_slot + shared_pages
        # generous fixed host budget: every group's pages fit with room to
        # spare on any preset (host DRAM is the cheap tier by construction)
        host_mb = 256.0
        pref_ab: dict = {}
        for mode, on, hmb in (("off", False, 0.0), ("hbm", True, 0.0),
                              ("hbm_host", True, host_mb)):
            pkw = dict(slots=p_slots, max_len=p_max_len,
                       max_prefill_batch=prefill_batch, decode_chunk=best[1],
                       prefill_buckets=[tail, shared_pages * ppage + tail],
                       decode_pipeline=pipeline, kv_layout="paged",
                       page_size=ppage, total_pages=p_pages,
                       prefix_cache=on, prefix_host_mb=hmb)
            hits0 = _tier_totals("app_tpu_prefix_hit_tokens")
            swap0 = _counter_total(container, "app_tpu_prefix_swapin_pages_total")
            try:
                engine = GenerateEngine(llama, cfg, params, container, **pkw)
                try:
                    engine.warmup()
                    engine.start()
                    # cold wave: concurrent fill — populates (and, via pool
                    # pressure, spills) the group prefixes; throughput number
                    t0 = time.monotonic()
                    reqs = [engine.submit(p, max_new_tokens=pref_new,
                                          timeout=timeout) for p in pprompts]
                    rr = [r.result(timeout) for r in reqs]
                    cold_elapsed = time.monotonic() - t0
                    cold_ttfts = [r["ttft_s"] for r in rr]
                    # warm probes: SEQUENTIAL re-issue of one prompt per
                    # group among the OLDEST half — the groups LRU pressure
                    # aged out of HBM, i.e. the population the spill tier
                    # exists to serve. Per-request TTFT with no queueing
                    # confound, which is the latency the tiers actually
                    # differ on: full re-prefill (off / evicted) vs
                    # swap-in + tail-chunk (host tier). Still-resident
                    # groups behave identically in both cached arms and
                    # would only dilute the p50.
                    warm_ttfts = [
                        engine.generate(pprompts[g * n_per], max_new_tokens=pref_new,
                                        timeout=timeout)["ttft_s"]
                        for g in range(max(1, groups // 2))
                    ]
                finally:
                    engine.stop()
                hits1 = _tier_totals("app_tpu_prefix_hit_tokens")
                arm = {
                    "req_per_s": round(len(pprompts) / cold_elapsed, 2),
                    "cold_ttft_p50_s": round(_percentile(cold_ttfts, 50), 4),
                    "warm_ttft_p50_s": round(_percentile(warm_ttfts, 50), 4),
                    "hit_tokens": {t: int(hits1.get(t, 0) - hits0.get(t, 0))
                                   for t in ("hbm", "host")},
                }
                if hmb:
                    arm["swapin_pages"] = int(_counter_total(
                        container, "app_tpu_prefix_swapin_pages_total") - swap0)
                pref_ab[mode] = arm
            except Exception as e:  # noqa: BLE001
                pref_ab[mode] = f"error: {e}"[:160]
        pref_ab["groups"] = groups
        pref_ab["cold_prompts"] = len(pprompts)
        pref_ab["warm_probes"] = max(1, groups // 2)
        pref_ab["total_pages"] = p_pages
        if (isinstance(pref_ab.get("hbm"), dict)
                and isinstance(pref_ab.get("hbm_host"), dict)):
            pref_ab["warm_ttft_speedup"] = round(
                pref_ab["hbm"]["warm_ttft_p50_s"]
                / max(pref_ab["hbm_host"]["warm_ttft_p50_s"], 1e-9), 3)
        extra["prefix_ab"] = pref_ab

    # multi-replica router A/B (ISSUE 7, ROADMAP O2): two in-process paged
    # replicas behind the REAL routing decision plane (gofr_tpu.router —
    # static two-member ring, no HTTP hop so the placement effect isn't
    # buried under proxy overhead). Tenant-skewed workload: each tenant
    # shares a multi-page prefix across its requests; the affinity arm
    # hashes each request's prefix chain key onto the ring so a tenant's
    # repeats land on the replica caching its prefix, the random arm
    # scatters them. Reported per arm: aggregate req/s over the skewed
    # wave, warm-TTFT p50 of per-tenant re-issues, and the prefix
    # hit-token ratio (cache hit tokens / prompt tokens submitted).
    if os.environ.get("GOFR_BENCH_ROUTER") == "1":
        from gofr_tpu.router import Router, RouterPolicy
        from gofr_tpu.tpu.engine import GenerateEngine

        tenants = 6
        ppage = 128 if cfg.max_seq_len >= 512 else 16
        shared_pages = 4
        tail = ppage // 2
        r_new = min(max_new, 8)
        n_router = max(2 * tenants, n_requests // 4)
        r_slots = max(2, min(best[0], 4))
        r_max_len = shared_pages * ppage + tail + r_new + 8
        pages_per_slot = -(-(r_max_len + best[1]) // ppage)
        # pool holds every tenant prefix + active slots: the A/B isolates
        # PLACEMENT (which replica is warm), not cache-pressure effects
        r_pages = r_slots * pages_per_slot + tenants * shared_pages
        shared_r = [rng.randint(1, cfg.vocab_size, size=shared_pages * ppage).tolist()
                    for _ in range(tenants)]
        # zipf-ish tenant skew: tenant i draws with weight 1/(i+1)
        weights = np.array([1.0 / (i + 1) for i in range(tenants)])
        draws = rng.choice(tenants, size=n_router, p=weights / weights.sum())
        rkw = dict(slots=r_slots, max_len=r_max_len,
                   max_prefill_batch=prefill_batch, decode_chunk=best[1],
                   prefill_buckets=[shared_pages * ppage + tail],
                   decode_pipeline=pipeline, kv_layout="paged",
                   page_size=ppage, total_pages=r_pages, prefix_cache=True)
        router_ab: dict = {}
        for mode in ("affinity", "random"):
            policy = RouterPolicy(page_size=ppage, mode=mode, jitter_s=0.0,
                                  replicas={"r0": "", "r1": ""}, seed=7)
            router = Router(container, policy=policy)
            hit0 = _counter_total(container, "app_tpu_prefix_hit_tokens")
            replicas: dict = {}
            try:
                try:
                    for n in ("r0", "r1"):
                        # built incrementally INSIDE the try: if the second
                        # engine fails to construct, the finally still stops
                        # the first instead of leaking its device pages into
                        # the next arm
                        replicas[n] = GenerateEngine(llama, cfg, params,
                                                     container, **rkw)
                    for eng in replicas.values():
                        eng.warmup()
                        eng.start()

                    placed = {"home": 0, "total": 0}

                    def _route(prompt):
                        rp = router.plan(router.shard_key(prompt))
                        placed["total"] += 1
                        placed["home"] += rp.targets[0].name == rp.home
                        return replicas[rp.targets[0].name]

                    prompt_toks = 0
                    # skewed wave: concurrent, repeats per tenant (cold on
                    # first touch, warm after) — the aggregate number
                    wave = []
                    for t in draws:
                        p = shared_r[t] + rng.randint(
                            1, cfg.vocab_size, size=tail).tolist()
                        prompt_toks += len(p)
                        wave.append(p)
                    t0 = time.monotonic()
                    reqs = [_route(p).submit(p, max_new_tokens=r_new,
                                             timeout=timeout) for p in wave]
                    for r in reqs:
                        r.result(timeout)
                    wave_elapsed = time.monotonic() - t0
                    # warm probes: one fresh-tail re-issue per tenant,
                    # sequential (no queueing confound) — TTFT is where
                    # landing on the warm replica pays
                    warm_ttfts = []
                    for t in range(tenants):
                        p = shared_r[t] + rng.randint(
                            1, cfg.vocab_size, size=tail).tolist()
                        prompt_toks += len(p)
                        warm_ttfts.append(_route(p).generate(
                            p, max_new_tokens=r_new, timeout=timeout)["ttft_s"])
                finally:
                    for eng in replicas.values():
                        eng.stop()
                hits = _counter_total(container, "app_tpu_prefix_hit_tokens") - hit0
                router_ab[mode] = {
                    "req_per_s": round(n_router / wave_elapsed, 2),
                    "warm_ttft_p50_s": round(_percentile(warm_ttfts, 50), 4),
                    "hit_token_ratio": round(hits / max(prompt_toks, 1), 4),
                    "affinity_hit_ratio": round(
                        placed["home"] / max(placed["total"], 1), 4),
                }
            except Exception as e:  # noqa: BLE001
                router_ab[mode] = f"error: {e}"[:160]
            finally:
                router.stop()
        router_ab["tenants"] = tenants
        router_ab["requests"] = n_router
        router_ab["shared_pages"] = shared_pages
        if (isinstance(router_ab.get("affinity"), dict)
                and isinstance(router_ab.get("random"), dict)):
            router_ab["warm_ttft_speedup"] = round(
                router_ab["random"]["warm_ttft_p50_s"]
                / max(router_ab["affinity"]["warm_ttft_p50_s"], 1e-9), 3)
            router_ab["hit_ratio_gain"] = round(
                router_ab["affinity"]["hit_token_ratio"]
                - router_ab["random"]["hit_token_ratio"], 4)
        extra["router"] = router_ab

    # heavy-tailed SLO workload (ISSUE 9, ROADMAP O5(b)): lognormal prompt/
    # output lengths, bursty arrivals (hot bursts separated by idle gaps),
    # and the PR 7 zipf tenant skew mapped onto QoS classes, judged by the
    # live per-class SLO engine (container.slo) — the standing evaluation
    # is "did each class MEET its objective", not a single req/s number.
    # Reported: per-class fast-window attainment/burn at the end of the
    # wave plus the PEAK burn rate observed per class along the way.
    if os.environ.get("GOFR_BENCH_SLO") == "1" and container.slo is not None:
        from gofr_tpu.tpu.engine import GenerateEngine

        s_classes = ("interactive", "default", "batch")
        s_tenants = 6
        n_slo = max(12, n_requests // 2)
        s_weights = np.array([1.0 / (i + 1) for i in range(s_tenants)])
        s_draws = rng.choice(s_tenants, size=n_slo,
                             p=s_weights / s_weights.sum())
        # heavy tails: lognormal around the headline lengths, clipped into
        # the engine's window budget (the p99 prompt is ~2x the median)
        max_plen = max(prompt_len,
                       min(2 * prompt_len, cfg.max_seq_len - max_new - 8))
        s_plens = np.clip(rng.lognormal(np.log(prompt_len), 0.5, n_slo)
                          .astype(int), 8, max_plen)
        s_nlens = np.clip(rng.lognormal(np.log(max_new), 0.5, n_slo)
                          .astype(int), 2, max_new)
        skw = dict(engine_kw(*best))
        skw.update(max_len=max_plen + max_new + 8,
                   prefill_buckets=sorted({prompt_len, max_plen}))
        burst = max(4, best[0] // 2)
        try:
            s_engine = GenerateEngine(llama, cfg, params, container, **skw)
            burn_peaks: dict = {}
            try:
                s_engine.warmup()
                s_engine.start()
                t0 = time.monotonic()
                done = 0
                while done < n_slo:
                    hi = min(done + burst, n_slo)
                    s_reqs = []
                    for i in range(done, hi):
                        p = rng.randint(1, cfg.vocab_size,
                                        size=int(s_plens[i])).tolist()
                        s_reqs.append(s_engine.submit(
                            p, max_new_tokens=int(s_nlens[i]), timeout=timeout,
                            qos_class=s_classes[s_draws[i] % len(s_classes)]))
                    for r in s_reqs:
                        r.result(timeout)
                    done = hi
                    if (done // burst) % 2 == 0:
                        time.sleep(0.05)  # the cold gap after a hot burst
                    for cname, objs in container.slo.snapshot().items():
                        for entry in objs.values():
                            b = entry["fast"]["burn_rate"]
                            if b is not None:
                                burn_peaks[cname] = max(
                                    burn_peaks.get(cname, 0.0), b)
                slo_elapsed = time.monotonic() - t0
            finally:
                s_engine.stop()
            per_class = {
                cname: {
                    oname: {"attainment": entry["fast"]["attainment"],
                            "burn_rate": entry["fast"]["burn_rate"],
                            "budget_remaining": entry["budget_remaining"]}
                    for oname, entry in objs.items() if entry["fast"]["total"]
                }
                for cname, objs in container.slo.snapshot().items()
            }
            extra["slo"] = {
                "requests": n_slo,
                "req_per_s": round(n_slo / slo_elapsed, 2),
                "prompt_len_p99": int(np.percentile(s_plens, 99)),
                "per_class": {c: v for c, v in per_class.items() if v},
                "burn_peaks": {c: round(v, 2)
                               for c, v in sorted(burn_peaks.items())},
            }
        except Exception as e:  # noqa: BLE001
            extra["slo"] = f"error: {e}"[:160]

    # cancel/retry-storm drill (ISSUE 10, closes ROADMAP O5(b)): the three
    # robustness contracts, judged with hard assertions rather than rates —
    #   (1) doomed work (deadline already expired at submission) is shed
    #       BEFORE taking a slot, with DeadlineExceeded/deadline_exceeded;
    #   (2) a chaos-scheduled client-disconnect storm mid-decode reclaims
    #       every slot and KV page (assert_page_refs_consistent after
    #       drain — zero leaks is the pass bar, not "mostly freed");
    #   (3) a synthetic 5xx retry storm through the shared RetryBudget
    #       amplifies by at most the budget fraction (Envoy-style cap).
    if os.environ.get("GOFR_BENCH_STORM") == "1":
        from gofr_tpu.fleet import chaos
        from gofr_tpu.http.errors import DeadlineExceeded
        from gofr_tpu.service.budget import RetryBudget
        from gofr_tpu.testutil import assert_page_refs_consistent
        from gofr_tpu.tpu.engine import GenerateEngine

        n_storm = max(12, n_requests // 2)
        st_kw = dict(engine_kw(*best))
        # the leak check is only meaningful on the paged layout — force it
        # (assert_page_refs_consistent is a documented no-op on slot KV)
        st_kw.update(kv_layout="paged", page_size=st_kw.get("page_size", 128))
        try:
            st_engine = GenerateEngine(llama, cfg, params, container, **st_kw)
            try:
                st_engine.warmup()
                st_engine.start()
                # (1) doomed-deadline shed: effective timeout <= 0 must be
                # rejected pre-slot, never queued to time out later
                shed = 0
                for _ in range(max(4, n_storm // 4)):
                    p = rng.randint(1, cfg.vocab_size, size=prompt_len).tolist()
                    try:
                        st_engine.submit(p, max_new_tokens=max_new, timeout=0.0)
                    except DeadlineExceeded:
                        shed += 1
                # (2) disconnect storm: every 2nd request's "client" goes
                # away mid-decode (deterministic chaos schedule), its
                # Request is cancelled cooperatively, and after the wave
                # drains the page table must balance exactly
                cancelled = 0
                with chaos.override("client.disconnect:drop,every=2"):
                    t0 = time.monotonic()
                    live = []
                    for _ in range(n_storm):
                        p = rng.randint(1, cfg.vocab_size,
                                        size=prompt_len).tolist()
                        r = st_engine.submit(p, max_new_tokens=max_new,
                                             timeout=timeout)
                        live.append((r, chaos.fire("client.disconnect")))
                    time.sleep(0.05)  # let decode get under way
                    for r, gone in live:
                        if gone:
                            r.cancel("client_disconnect")
                            cancelled += 1
                    for r, gone in live:
                        if not gone:
                            r.result(timeout)
                    storm_elapsed = time.monotonic() - t0
                deadline_t = time.monotonic() + 10.0
                while any(s is not None
                          for s in getattr(st_engine, "slots", [])) and \
                        time.monotonic() < deadline_t:
                    time.sleep(0.02)
                assert_page_refs_consistent(st_engine)
            finally:
                st_engine.stop()
            # (3) retry amplification under a storm where EVERY attempt
            # fails: with fraction f the budget must cap retries at
            # max(min_retries, f * window originals)
            frac, n_orig = 0.2, 200
            rb = RetryBudget(fraction=frac, min_retries=3, window_s=60.0)
            for _ in range(n_orig):
                rb.note_request()
            granted = sum(1 for _ in range(n_orig) if rb.try_spend())
            cap = max(3, int(frac * n_orig))
            if granted > cap:
                raise AssertionError(
                    f"retry budget leaked: {granted} retries > cap {cap}")
            extra["storm"] = {
                "requests": n_storm,
                "req_per_s": round(n_storm / storm_elapsed, 2),
                "deadline_shed_pre_slot": shed,
                "disconnect_cancelled": cancelled,
                "page_refs_consistent": True,
                "retry_amplification": round(granted / n_orig, 3),
                "retry_budget_fraction": frac,
            }
        except Exception as e:  # noqa: BLE001
            extra["storm"] = f"error: {e}"[:160]

    # trace-driven diurnal elasticity harness (ISSUE 11, ROADMAP O2): a 24h
    # arrival curve compressed into GOFR_BENCH_DIURNAL_S seconds — sinusoidal
    # "hours" with two 3x burst hours and zipf tenant→class skew — replayed
    # IDENTICALLY against two fleets: "static" (max replicas, always on) and
    # "elastic" (the fleet/autoscaler.py control loop starting from one
    # replica). Judged on both axes the autoscaler trades between: per-class
    # SLO attainment (did elasticity cost the users anything) and
    # chip-seconds-per-request (what did static provisioning waste).
    if os.environ.get("GOFR_BENCH_DIURNAL") == "1":
        from gofr_tpu.container import new_mock_container as _fresh_container
        from gofr_tpu.fleet.autoscaler import (
            AutoscalePolicy,
            Autoscaler,
            FleetSignals,
            LocalEngineFleet,
        )
        from gofr_tpu.tpu.engine import GenerateEngine

        d_total_s = float(os.environ.get("GOFR_BENCH_DIURNAL_S", "60"))
        d_reqs = int(os.environ.get("GOFR_BENCH_DIURNAL_REQUESTS",
                                    str(max(24, 3 * n_requests))))
        d_max = int(os.environ.get("GOFR_BENCH_DIURNAL_MAX", "3"))
        d_slots = int(os.environ.get("GOFR_BENCH_DIURNAL_SLOTS",
                                     str(min(4, best[0]))))
        d_classes = ("interactive", "default", "batch")
        # the trace is built ONCE — both arms replay identical arrival
        # times, classes, prompts and output lengths
        d_hours = np.arange(24)
        d_weights = 1.0 + 0.9 * np.sin(2 * np.pi * (d_hours - 6) / 24.0)
        d_burst_hours = rng.choice(24, size=2, replace=False)
        d_weights[d_burst_hours] *= 3.0
        d_per_hour = rng.multinomial(d_reqs, d_weights / d_weights.sum())
        d_hour_s = d_total_s / 24.0
        d_tw = np.array([1.0 / (i + 1) for i in range(6)])  # zipf tenants
        d_tw = d_tw / d_tw.sum()
        d_trace = []
        for h, cnt in enumerate(d_per_hour):
            for t_off in np.sort(rng.uniform(0, d_hour_s, size=int(cnt))):
                tenant = int(rng.choice(6, p=d_tw))
                plen = int(np.clip(rng.lognormal(
                    np.log(max(8, prompt_len // 2)), 0.4), 8, prompt_len))
                nlen = int(np.clip(rng.lognormal(
                    np.log(max(2, max_new // 2)), 0.4), 2, max_new))
                d_trace.append((
                    h * d_hour_s + float(t_off),
                    d_classes[tenant % len(d_classes)],
                    rng.randint(1, cfg.vocab_size, size=plen).tolist(),
                    nlen))

        def _run_diurnal_arm(elastic: bool) -> dict:
            # fresh container per arm: its SLO plane is the judge, so the
            # arms must not share windows. CPU-scale objectives + a short
            # fast window so a compressed trace can actually burn budget.
            cont = _fresh_container({
                "SLO_FAST_WINDOW_S": str(max(5.0, d_total_s / 8.0)),
                "SLO_MIN_SAMPLES": "5",
                "SLO_INTERACTIVE_TTFT_MS": os.environ.get(
                    "GOFR_BENCH_DIURNAL_TTFT_MS", "1500"),
            })

            def factory(name: str) -> GenerateEngine:
                # the warm-spare contract: weights are already in `params`
                # and warmup() resolves its attention pins from the shared
                # GOFR_AUTOTUNE_CACHE, so a mid-trace spawn is near-free
                eng = GenerateEngine(llama, cfg, params, cont,
                                     **engine_kw(d_slots, best[1]))
                eng.warmup()
                eng.start()
                return eng

            fleet = LocalEngineFleet(factory, name_prefix=f"d{int(elastic)}-")
            n_start = 1 if elastic else d_max
            for _ in range(n_start):
                fleet.spawn()
            scaler = None
            if elastic:
                policy = AutoscalePolicy(
                    min_replicas=1, max_replicas=d_max,
                    burn_out=1.5, burn_in=1.0,
                    wait_out_s=0.5, wait_in_s=0.1,
                    sustain_s=max(0.5, d_total_s / 60.0),
                    idle_s=max(2.0, d_total_s / 12.0),
                    cooldown_out_s=max(1.0, d_total_s / 30.0),
                    cooldown_in_s=max(2.0, d_total_s / 15.0),
                    interval_s=0.25, drain_timeout_s=timeout)

                def signals() -> FleetSignals:
                    pr = (cont.slo.pressure() if cont.slo is not None
                          else {"burn": None})
                    return FleetSignals(
                        burn=pr.get("burn"),
                        predicted_wait_s=fleet.max_predicted_wait(),
                        replicas=fleet.count(), age_s=0.0)

                scaler = Autoscaler(fleet, policy, signals=signals,
                                    logger=cont.logger,
                                    metrics=cont.metrics).start()
            chip_s, errors, done = 0.0, 0, 0
            lo = hi = fleet.count()
            d_live = []
            t0 = last = time.monotonic()
            try:
                for t_at, cls, p, nlen in d_trace:
                    while True:
                        now_t = time.monotonic()
                        chip_s += fleet.count() * (now_t - last)
                        last = now_t
                        lo, hi = min(lo, fleet.count()), max(hi, fleet.count())
                        if now_t - t0 >= t_at:
                            break
                        time.sleep(min(0.02, t_at - (now_t - t0)))
                    # least-backlog placement with drain/shed spillover —
                    # the in-process stand-in for the router's ring+spill
                    for eng in sorted(fleet.engines(),
                                      key=lambda e: e._backlog()):
                        try:
                            d_live.append(eng.submit(
                                p, max_new_tokens=nlen, timeout=timeout,
                                qos_class=cls))
                            break
                        except Exception:  # noqa: BLE001 - draining/shedding
                            continue
                    else:
                        errors += 1
                for r in d_live:
                    try:
                        r.result(timeout)
                        done += 1
                    except Exception:  # noqa: BLE001 - requeue raced retire
                        errors += 1
                    now_t = time.monotonic()
                    chip_s += fleet.count() * (now_t - last)
                    last = now_t
                elapsed_d = time.monotonic() - t0
                total_spawned = fleet._counter
                final_count = fleet.count()
            finally:
                if scaler is not None:
                    scaler.stop()
                fleet.stop_all()
            per_class = {
                cname: {
                    oname: {"attainment": e["fast"]["attainment"],
                            "burn_rate": e["fast"]["burn_rate"]}
                    for oname, e in objs.items() if e["fast"]["total"]}
                for cname, objs in cont.slo.snapshot().items()}
            return {
                "requests": len(d_trace), "completed": done, "errors": errors,
                "elapsed_s": round(elapsed_d, 2),
                "chip_seconds": round(chip_s, 2),
                "chip_seconds_per_request": round(chip_s / max(1, done), 4),
                "replicas_min": lo, "replicas_max": hi,
                "scale_outs": total_spawned - n_start,
                "scale_ins": total_spawned - final_count,
                "per_class": {c: v for c, v in per_class.items() if v},
            }

        try:
            d_arms = {"elastic": _run_diurnal_arm(True),
                      "static": _run_diurnal_arm(False)}
            d_arms["trace"] = {
                "compressed_s": d_total_s, "requests": len(d_trace),
                "burst_hours": sorted(int(h) for h in d_burst_hours),
                "max_replicas": d_max, "slots_per_replica": d_slots,
            }
            es, ss = d_arms["elastic"], d_arms["static"]
            if es["completed"] and ss["completed"]:
                d_arms["chip_seconds_saved_ratio"] = round(
                    1.0 - es["chip_seconds"] / max(ss["chip_seconds"], 1e-9), 4)
            extra["autoscale"] = d_arms
        except Exception as e:  # noqa: BLE001
            extra["autoscale"] = f"error: {e}"[:160]

    # disaggregated prefill/decode A/B (ISSUE 12): the interference
    # question — how much does a concurrent prefill wave degrade RESIDENT
    # decode streams? "colocated" serves both phases on one engine;
    # "disagg" role-splits them: a prefill worker exports each prompt's
    # paged KV over loopback TCP to a decode worker (tpu/handoff.py) that
    # owns the token streams. Each arm measures resident TPOT twice —
    # quiet, then under the wave — so the archived degradation ratio
    # isolates interference from raw speed. NB: on the CPU fallback both
    # "devices" share the host cores, so the disagg arm's isolation win is
    # only meaningful on real accelerators; the CPU smoke checks structure
    # (both arms archived, handoff stats present, token-exactness).
    if os.environ.get("GOFR_BENCH_DISAGG") == "1":
        import threading as _threading

        from gofr_tpu.container import new_mock_container as _fresh_container
        from gofr_tpu.tpu.engine import GenerateEngine

        g_res = int(os.environ.get("GOFR_BENCH_DISAGG_RESIDENTS", "4"))
        g_wave = int(os.environ.get("GOFR_BENCH_DISAGG_WAVE",
                                    str(max(4, n_requests // 2))))
        g_page = 8 if on_cpu else 128
        g_plen = max(g_page, (prompt_len // g_page) * g_page)
        g_new = max(8, max_new)

        def _disagg_kw() -> dict:
            kw = dict(engine_kw(*best))
            pages_per_seq = (g_plen + g_new) // g_page + 2
            kw.update(kv_layout="paged", page_size=g_page,
                      total_pages=max(64, 2 * best[0] * pages_per_seq),
                      max_len=g_plen + g_new + 8, prefill_buckets=[g_plen])
            return kw

        # two disjoint resident sets (quiet phase / wave phase — a reused
        # prompt would be a device-tier prefix hit the second time) and the
        # wave, identical across arms
        g_sets = [[rng.randint(1, cfg.vocab_size, size=g_plen).tolist()
                   for _ in range(g_res)] for _ in range(2)]
        g_wave_prompts = [rng.randint(1, cfg.vocab_size, size=g_plen).tolist()
                          for _ in range(g_wave)]

        def _timed_results(reqs: list, t0s: list) -> list[dict]:
            """Per-request completion times via one waiter thread each —
            serial .result() gathering would timestamp request i with
            request i-1's drain."""
            out: list = [None] * len(reqs)

            def _wait(i: int) -> None:
                r = reqs[i].result(timeout)
                out[i] = (r, time.monotonic() - t0s[i])

            ths = [_threading.Thread(target=_wait, args=(i,))
                   for i in range(len(reqs))]
            for t in ths:
                t.start()
            for t in ths:
                t.join(timeout + 5)
            if any(o is None for o in out):
                raise RuntimeError("disagg bench: resident stream hung")
            return [{"tokens": r["tokens"], "ttft_s": r["ttft_s"],
                     "total_s": total} for r, total in out]

        def _phase(decode_eng, wave_eng, residents: list,
                   wave: bool) -> tuple[dict, list]:
            t0s: list[float] = []
            reqs = []
            for p in residents:
                t0s.append(time.monotonic())
                reqs.append(decode_eng.submit(p, max_new_tokens=g_new,
                                              timeout=timeout))
            wave_reqs = []
            tw0 = time.monotonic()
            if wave:
                # the wave lands while the residents are mid-stream; on the
                # wave engine a prefill-role request completes at its first
                # token (finish_reason=handoff), a colocated one decodes a
                # 2-token stub so both arms' waves are prefill-dominated
                wave_reqs = [wave_eng.submit(p, max_new_tokens=2,
                                             timeout=timeout)
                             for p in g_wave_prompts]
            rs = _timed_results(reqs, t0s)
            for r in wave_reqs:
                r.result(timeout)
            wave_s = time.monotonic() - tw0
            tpots = [(r["total_s"] - r["ttft_s"]) / (len(r["tokens"]) - 1)
                     for r in rs if len(r["tokens"]) > 1]
            m = {"ttft_p50_s": round(_percentile([r["ttft_s"] for r in rs], 50), 4),
                 "ttft_p99_s": round(_percentile([r["ttft_s"] for r in rs], 99), 4),
                 "tpot_p50_s": round(_percentile(tpots, 50), 5),
                 "tpot_p99_s": round(_percentile(tpots, 99), 5)}
            if wave:
                m["wave_requests"] = len(wave_reqs)
                m["wave_elapsed_s"] = round(wave_s, 3)
            return m, [r["tokens"] for r in rs]

        def _run_disagg_arm(split: bool) -> tuple[dict, list]:
            cont = _fresh_container()
            kw = _disagg_kw()
            if split:
                dec = GenerateEngine(llama, cfg, params, cont,
                                     role="decode", **kw)
                pre = GenerateEngine(llama, cfg, params, _fresh_container(),
                                     role="prefill",
                                     handoff_target=dec.handoff_addr, **kw)
                engines = [pre, dec]
            else:
                pre = dec = GenerateEngine(llama, cfg, params, cont, **kw)
                engines = [dec]
            try:
                for e in engines:
                    e.warmup()
                    e.start()
                if split:
                    # stage both resident sets through the prefill worker:
                    # their KV chains land on the decode side as host-tier
                    # prefix nodes, which is what makes the decode-side
                    # resident submissions decode-only work
                    for p in g_sets[0] + g_sets[1]:
                        r = pre.generate(p, max_new_tokens=2, timeout=timeout)
                        if r.get("finish_reason") != "handoff":
                            raise RuntimeError(
                                f"prefill worker decoded locally: {r.get('finish_reason')}")
                quiet, toks = _phase(dec, pre, g_sets[0], wave=False)
                loaded, _ = _phase(dec, pre, g_sets[1], wave=True)
                arm = {"quiet": quiet, "wave": loaded,
                       "tpot_p99_degradation": round(
                           loaded["tpot_p99_s"] / max(quiet["tpot_p99_s"], 1e-9), 3)}
                if split:
                    arm["handoff"] = {"export": pre.handoff_stats().get("export"),
                                      "import": dec.handoff_stats().get("import")}
                return arm, toks
            finally:
                for e in engines:
                    e.stop()

        try:
            disagg: dict = {"residents": g_res, "prompt_len": g_plen,
                            "max_new": g_new, "page_size": g_page}
            colo_arm, colo_toks = _run_disagg_arm(False)
            split_arm, split_toks = _run_disagg_arm(True)
            disagg["colocated"] = colo_arm
            disagg["disagg"] = split_arm
            # same seeded prompts, same params: the role-split pipeline must
            # reproduce the colocated streams token for token
            disagg["token_exact"] = bool(colo_toks == split_toks)
            extra["disagg"] = disagg
        except Exception as e:  # noqa: BLE001
            extra["disagg"] = f"error: {e}"[:160]

    # streaming KV handoff A/B (ISSUE 18): blob (GOFR-HANDOFF1, streams=0)
    # vs streaming (GOFR-HANDOFF2) across prompt-length buckets. The wire
    # is emulated via HANDOFF_PACE_MBPS, calibrated to 0.75x the measured
    # per-chunk prefill compute so transfer CAN hide behind compute: the
    # blob arm's decode-side TTFT then grows linearly in pages (the whole
    # frame ships after activation) while the streaming arm's stays flat
    # (only the in-flight tail remains at activation) — the flattening IS
    # the perf claim, asserted by the bench-handoff-smoke CI job.
    if os.environ.get("GOFR_BENCH_HANDOFF_STREAM") == "1":
        from gofr_tpu.container import new_mock_container as _fresh_container
        from gofr_tpu.tpu.engine import GenerateEngine

        h_page = 8 if on_cpu else 128
        # prompt length in pages; the top bucket must clear the model's
        # max_seq_len (tiny CPU config caps at 120 positions)
        h_buckets = [2, 4, 8, 12]
        h_reps = int(os.environ.get("GOFR_BENCH_HANDOFF_REPS", "3"))
        h_new = 4

        def _handoff_kw(**over) -> dict:
            kw = dict(engine_kw(*best))
            # chunked prefill at one page per chunk, one page per wire
            # chunk: maximum overlap granularity for the streaming arm
            kw.update(kv_layout="paged", page_size=h_page,
                      total_pages=max(64, 4 * h_buckets[-1]),
                      max_len=h_buckets[-1] * h_page + h_new + 8,
                      prefill_buckets=[h_page], handoff_chunk_pages=1)
            kw.update(over)
            return kw

        h_prompts = {b: [rng.randint(1, cfg.vocab_size,
                                     size=b * h_page).tolist()
                         for _ in range(h_reps)] for b in h_buckets}

        def _ls_slope(xs: list, ys: list) -> float:
            xm = sum(xs) / len(xs)
            ym = sum(ys) / len(ys)
            den = sum((x - xm) ** 2 for x in xs) or 1e-12
            return sum((x - xm) * (y - ym) for x, y in zip(xs, ys)) / den

        def _run_handoff_arm(streams: int, pace: float, colo_toks: dict):
            dec = GenerateEngine(llama, cfg, params, _fresh_container(),
                                 role="decode", **_handoff_kw())
            pre = GenerateEngine(
                llama, cfg, params, _fresh_container(), role="prefill",
                handoff_target=dec.handoff_addr,
                **_handoff_kw(handoff_streams=streams,
                              handoff_pace_mbps=pace))
            exact = True
            by_bucket: dict = {}
            try:
                for e in (pre, dec):
                    e.warmup()
                    e.start()
                for b in h_buckets:
                    ttfts = []
                    for i, p in enumerate(h_prompts[b]):
                        t_sub = time.monotonic()
                        res = pre.generate(p, max_new_tokens=h_new,
                                           timeout=timeout)
                        t_done = time.monotonic()
                        if res.get("finish_reason") != "handoff":
                            raise RuntimeError(
                                "prefill worker decoded locally: "
                                f"{res.get('finish_reason')}")
                        # decode-side TTFT: the tail between activation and
                        # transfer-complete (what the blob protocol pays in
                        # full, the stream only for in-flight chunks) plus
                        # the decode worker's own prefix-hit first step
                        tail = max(0.0, (t_done - t_sub) - res["ttft_s"])
                        out = dec.generate(p, max_new_tokens=h_new,
                                           timeout=timeout)
                        ttfts.append(tail + out["ttft_s"])
                        want = colo_toks[b][i]
                        if out["tokens"] != want or res["tokens"] != [want[0]]:
                            exact = False
                    by_bucket[str(b)] = {
                        "p50_s": round(_percentile(ttfts, 50), 4),
                        "p99_s": round(_percentile(ttfts, 99), 4)}
                p50s = [by_bucket[str(b)]["p50_s"] for b in h_buckets]
                st = pre.handoff_stats().get("export") or {}
                return {
                    "ttft_decode_by_bucket_pages": by_bucket,
                    "flatness_p50": round(p50s[-1] / max(p50s[0], 1e-9), 3),
                    "slope_s_per_page": round(
                        _ls_slope([float(b) for b in h_buckets], p50s), 6),
                    "mode": st.get("mode"), "streams": st.get("streams"),
                    "overlap_ratio": st.get("overlap_ratio"),
                    "overlap_bytes": st.get("overlap_bytes"),
                }, exact
            finally:
                pre.stop()
                dec.stop()

        try:
            # colocated reference: token-exact oracle + per-chunk compute
            # calibration for the emulated wire
            colo = GenerateEngine(llama, cfg, params, _fresh_container(),
                                  **_handoff_kw())
            colo_toks: dict = {}
            try:
                colo.warmup()
                colo.start()
                rcal = colo.generate(h_prompts[h_buckets[-1]][0],
                                     max_new_tokens=1, timeout=timeout)
                per_chunk = max(1e-4, rcal["ttft_s"] / h_buckets[-1])
                for b in h_buckets:
                    colo_toks[b] = [
                        colo.generate(p, max_new_tokens=h_new,
                                      timeout=timeout)["tokens"]
                        for p in h_prompts[b]]
                page_bytes = int(colo._page_bytes)
            finally:
                colo.stop()
            wire_per_page = 0.75 * per_chunk
            pace = page_bytes / (wire_per_page * 1e6)
            blob_arm, blob_exact = _run_handoff_arm(0, pace, colo_toks)
            stream_arm, stream_exact = _run_handoff_arm(2, pace, colo_toks)
            extra["handoff_stream"] = {
                "page_size": h_page, "reps": h_reps,
                "buckets_pages": h_buckets,
                "per_chunk_s": round(per_chunk, 5),
                "pace_mbps": round(pace, 3),
                "blob": blob_arm, "stream": stream_arm,
                "token_exact": bool(blob_exact and stream_exact),
            }
        except Exception as e:  # noqa: BLE001
            extra["handoff_stream"] = f"error: {e}"[:160]

    # multi-LoRA consolidation A/B (ISSUE 16): the COGS question — what
    # does serving N tenants' adapters cost on ONE multiplexed engine vs
    # N dedicated engines? Both arms serve the identical seeded workload
    # (requests round-robined across adapters) to completion (equal
    # attainment), so the comparison is pure chip-seconds/request: the
    # dedicated arm pays N sets of idle decode slots and N prefill
    # pipelines, the multiplexed arm co-batches all tenants into shared
    # steps (lm_head-only LoRA gather; gofr_tpu/adapters). Token-exactness
    # per arm pair is archived — consolidation must not change answers.
    if os.environ.get("GOFR_BENCH_ADAPTERS") == "1":
        from gofr_tpu.adapters import random_adapter as _rand_ad
        from gofr_tpu.container import new_mock_container as _ad_container
        from gofr_tpu.tpu.engine import GenerateEngine as _AdEngine

        n_ad = max(2, int(os.environ.get("GOFR_BENCH_ADAPTERS_N", "3")))
        ad_rank = 8 if on_cpu else 16
        ad_specs = [_rand_ad(f"tenant{i}", cfg.hidden_size, cfg.vocab_size,
                             rank=ad_rank, seed=100 + i)
                    for i in range(n_ad)]
        ad_reqs = max(n_ad * 2, n_requests // 2)
        ad_jobs = [(rng.randint(1, cfg.vocab_size,
                                size=prompt_len).tolist(),
                    ad_specs[i % n_ad].name)
                   for i in range(ad_reqs)]

        def _device_s(eng) -> float:
            if eng.perf is None:
                return 0.0
            tot = eng.perf.window_totals(time.monotonic())
            return sum(rec["device_s"] for rec in tot["kinds"].values())

        def _run_adapter_arm(mux: bool) -> tuple[dict, dict]:
            kw = dict(engine_kw(*best))
            kw.update(adapter_rank=ad_rank,
                      adapter_slots=(n_ad + 1) if mux else 2)
            toks: dict = {}
            if mux:
                engines = {None: _AdEngine(llama, cfg, params,
                                           _ad_container(), **kw)}
                for s in ad_specs:
                    engines[None].register_adapter(s)
            else:
                engines = {}
                for s in ad_specs:
                    engines[s.name] = _AdEngine(llama, cfg, params,
                                                _ad_container(), **kw)
                    engines[s.name].register_adapter(s)
            try:
                for e in engines.values():
                    e.warmup()
                    e.start()
                t0 = time.monotonic()
                pend = [(i, engines[None if mux else name].submit(
                            p, max_new_tokens=max_new, timeout=timeout,
                            adapter_id=name))
                        for i, (p, name) in enumerate(ad_jobs)]
                for i, r in pend:
                    toks[i] = r.result(timeout)["tokens"]
                elapsed = time.monotonic() - t0
                dev_s = sum(_device_s(e) for e in engines.values())
                arm = {"engines": len(engines),
                       "elapsed_s": round(elapsed, 3),
                       "req_per_s": round(len(ad_jobs) / elapsed, 3),
                       "device_s": round(dev_s, 3),
                       "chip_s_per_req": round(dev_s / len(ad_jobs), 5)}
                if mux:
                    st = next(iter(engines.values())).adapter_stats()
                    arm["pool"] = {"uploads": st["pool"]["uploads"],
                                   "evictions": st["pool"]["evictions"]}
                return arm, toks
            finally:
                for e in engines.values():
                    e.stop()

        try:
            mux_arm, mux_toks = _run_adapter_arm(True)
            ded_arm, ded_toks = _run_adapter_arm(False)
            extra["adapters"] = {
                "n_adapters": n_ad, "requests": ad_reqs, "rank": ad_rank,
                "multiplexed": mux_arm, "dedicated": ded_arm,
                # < 1.0 = consolidation serves the same attainment on
                # fewer chip-seconds (the headline per-tenant COGS win)
                "chip_s_ratio": round(
                    mux_arm["chip_s_per_req"]
                    / max(ded_arm["chip_s_per_req"], 1e-9), 3),
                # co-batching tenants must not change any tenant's answer
                "token_exact": bool(mux_toks == ded_toks),
            }
        except Exception as e:  # noqa: BLE001
            extra["adapters"] = f"error: {e}"[:160]

    # NB: on the CPU fallback the "device" compute runs on the same host
    # cores as the packing/readback, so overlap has nothing to hide behind
    # and "off" can win; the A/B is meaningful on a real accelerator link
    # (the round-3 tunnel measured ~100ms RTT per sync — the thing depth>=2
    # removes from the critical path).
    # mixed-arrival overlap A/B: paced arrivals of short prompts plus
    # chunked-long prompts (every 4th is ~2x the bucket, taking the chunked
    # prefill path) against active decode slots. "on" = the unified async
    # pipeline (depth >= 2: prefill futures ride the in-flight queue and
    # read back overlapped with decode dispatch); "off" = depth 1 (every
    # dispatch drains synchronously — the pre-unification stall-per-arrival
    # behavior). Decode throughput collapse under prefill traffic is what
    # this measures; TTFT is recorded so the overlap win is shown not to
    # come at first-token latency's expense.
    if os.environ.get("GOFR_BENCH_OVERLAP_AB") == "1":
        n_mix = max(8, n_requests // 4)
        # long prompts must clear the bucket ladder but leave decode+chunk
        # headroom inside cfg.max_seq_len (tiny CPU configs are tight); if
        # the config can't fit any, the A/B degenerates to all-short — run
        # it anyway but REPORT the degeneration instead of implying the
        # chunked path was exercised
        long_len = min(2 * prompt_len, cfg.max_seq_len - max_new - 4 * best[1] - 8)
        use_long = long_len > prompt_len
        mix = []
        n_long = 0
        for i in range(n_mix):
            if i % 4 == 3 and use_long:
                size = long_len
                n_long += 1
            else:
                size = prompt_len
            mix.append(rng.randint(1, cfg.vocab_size, size=size).tolist())
        arrival_env = os.environ.get("GOFR_BENCH_ARRIVAL_MS")
        arrival_s = (float(arrival_env) / 1000.0 if arrival_env
                     else max(0.001, elapsed / n_requests / 2))
        overlap_ab: dict = {}
        for mode, depth_ab in (("on", max(2, pipeline)), ("off", 1)):
            okw = dict(engine_kw(*best))
            okw.update(decode_pipeline=depth_ab,
                       max_len=max(long_len, prompt_len) + max_new + 8,
                       prefill_buckets=[prompt_len])
            try:
                mm = _run_mixed(okw, cfg, params, container, llama, mix,
                                max_new, timeout, arrival_s)
                overlap_ab[mode] = {
                    "req_per_s": round(len(mix) / mm["elapsed"], 3),
                    "decode_tokens_per_s": round(mm["new_tokens"] / mm["elapsed"], 1),
                    "ttft_p50_s": round(_percentile(mm["ttfts"], 50), 4),
                    "ttft_p99_s": round(_percentile(mm["ttfts"], 99), 4),
                }
            except Exception as e:  # noqa: BLE001
                overlap_ab[mode] = f"error: {e}"[:160]
        overlap_ab["arrival_ms"] = round(arrival_s * 1000, 2)
        overlap_ab["long_prompts"] = n_long
        overlap_ab["long_prompt_len"] = int(long_len) if use_long else None
        if (isinstance(overlap_ab.get("on"), dict)
                and isinstance(overlap_ab.get("off"), dict)):
            overlap_ab["speedup"] = round(
                overlap_ab["on"]["req_per_s"] / max(overlap_ab["off"]["req_per_s"], 1e-9), 3)
        # the layout/spec config the A/B actually ran under (ISSUE 13: spec
        # rounds ride the same pipeline on BOTH layouts now, so the overlap
        # claim is meaningful with GOFR_BENCH_KV=paged GOFR_BENCH_SPEC>0 too)
        overlap_ab["kv_layout"] = kv_layout
        if spec_tokens:
            overlap_ab["spec_tokens"] = spec_tokens
        extra["overlap_ab"] = overlap_ab

    # spec-on/off overlap A/B (ISSUE 13): paced mixed arrivals with
    # speculative rounds ON vs OFF at the configured KV layout. Before the
    # pipeline fold, the paged spec path dispatched synchronously — every
    # round stalled prefill admission for a full device round trip; now
    # both layouts dispatch spec rounds onto the bounded in-flight queue,
    # and this A/B is the archived evidence that spec no longer serializes
    # the device loop under arrival pressure (same CPU caveat as above).
    if os.environ.get("GOFR_BENCH_SPEC_AB") == "1":
        st_ab = spec_tokens or 3
        short = prompts[: max(8, n_requests // 4)]
        arrival_s = max(0.001, elapsed / n_requests / 2)
        spec_ab: dict = {"kv_layout": kv_layout, "spec_tokens": st_ab,
                         "arrival_ms": round(arrival_s * 1000, 2)}
        for mode, stv in (("on", st_ab), ("off", 0)):
            skw = dict(engine_kw(*best))
            skw.pop("spec_tokens", None)
            if stv:
                skw["spec_tokens"] = stv
            try:
                mm = _run_mixed(skw, cfg, params, container, llama, short,
                                max_new, timeout, arrival_s)
                spec_ab[mode] = {
                    "req_per_s": round(len(short) / mm["elapsed"], 3),
                    "decode_tokens_per_s": round(mm["new_tokens"] / mm["elapsed"], 1),
                    "ttft_p50_s": round(_percentile(mm["ttfts"], 50), 4),
                    "ttft_p99_s": round(_percentile(mm["ttfts"], 99), 4),
                }
            except Exception as e:  # noqa: BLE001
                spec_ab[mode] = f"error: {e}"[:160]
        if (isinstance(spec_ab.get("on"), dict)
                and isinstance(spec_ab.get("off"), dict)):
            spec_ab["speedup"] = round(
                spec_ab["on"]["req_per_s"] / max(spec_ab["off"]["req_per_s"], 1e-9), 3)
        extra["spec_ab"] = spec_ab

    # Online step-controller A/B (gofr_tpu.control): does closing the perf
    # plane into actuation actually pay? One shifting workload — a burst
    # phase (high occupancy, prefill pressure), a paced phase, then a
    # trickle (near-empty pipeline) — is replayed IDENTICALLY against every
    # static (pipeline_depth, prefill_batch) setting inside the boot
    # envelope and against a controlled engine that boots at the envelope
    # ceiling but is immediately parked at the PESSIMAL corner via
    # request_knobs, so any decent score REQUIRES the controller to climb
    # (and guarantees the decision ring is non-empty). All arms run greedy,
    # so token-exactness across every arm is the live proof that knob moves
    # never touch the token stream; the static ceiling arm doubles as the
    # CONTROL_ENABLE=0 off-path check (no controller object constructed).
    if os.environ.get("GOFR_BENCH_CONTROLLER") == "1":
        from gofr_tpu.container import new_mock_container as _ctl_container
        from gofr_tpu.control.controller import StepController as _StepCtl
        from gofr_tpu.tpu.engine import GenerateEngine

        c_interval = float(os.environ.get(
            "GOFR_BENCH_CONTROLLER_INTERVAL_S", "0.3"))
        c_tol = float(os.environ.get("GOFR_BENCH_CONTROLLER_TOL", "0.25"))
        # the trace must SPAN wall time, not just offer work: the
        # controller ticks on real seconds, so the paced + trickle phases
        # are stretched over c_span to leave room for ~c_span/interval
        # evidence windows (a burst-only trace finishes in milliseconds on
        # the tiny CPU model and the controller never gets to act)
        c_span = float(os.environ.get("GOFR_BENCH_CONTROLLER_SPAN_S", "8"))
        c_depth_max, c_batch_max = 2, 2
        # the trace is built once; every arm replays the same arrival
        # times, prompts and output lengths
        c_n = max(12, n_requests)
        c_tail = max(4, c_n // 2)
        c_trace = []
        t_cursor = 0.0
        for count, gap in ((c_n, 0.0),
                           (c_n, 0.5 * c_span / c_n),
                           (c_tail, 0.5 * c_span / c_tail)):
            for _ in range(count):
                c_trace.append((
                    t_cursor,
                    rng.randint(1, cfg.vocab_size, size=prompt_len).tolist()))
                t_cursor += gap
            t_cursor += 2 * c_interval  # phase boundary breather

        def _run_ctl_arm(depth_a: int, batch_a: int, controlled: bool) -> tuple:
            cont = _ctl_container({
                # smoke-speed control plane: sub-second ticks, a low
                # evidence floor, and short cooldown/backoff so the
                # compressed trace leaves room for several trials
                "CONTROL_INTERVAL_S": str(c_interval),
                "CONTROL_SUSTAIN_S": str(c_interval),
                "CONTROL_COOLDOWN_S": str(c_interval),
                "CONTROL_BACKOFF_S": str(4 * c_interval),
                "CONTROL_MIN_STEPS": "4",
                "CONTROL_EPSILON": "0.02",
                "CONTROL_KNOBS": "pipeline_depth,prefill_batch",
            })
            ckw = dict(engine_kw(*best))
            ckw.update(decode_pipeline=depth_a, max_prefill_batch=batch_a,
                       control_enable=controlled)
            eng = GenerateEngine(llama, cfg, params, cont, **ckw)
            try:
                eng.warmup()
                eng.start()
                eng.generate(c_trace[0][1], max_new_tokens=2, timeout=timeout)
                if controlled:
                    # pessimal start inside the envelope: the controller
                    # has to earn its way back to the good corner
                    eng.request_knobs(pipeline_depth=1, prefill_batch=1)
                t0c = time.monotonic()
                reqs = []
                for t_at, p in c_trace:
                    delay = t0c + t_at - time.monotonic()
                    if delay > 0:
                        time.sleep(delay)
                    reqs.append(eng.submit(p, max_new_tokens=max_new,
                                           timeout=timeout))
                toks = [r.result(timeout)["tokens"] for r in reqs]
                elapsed_c = time.monotonic() - t0c
                bands = (eng.perf.band_totals(time.monotonic())
                         if eng.perf is not None else {})
                ev = _StepCtl._summarize(bands)
                rep = eng.control_report()
            finally:
                eng.stop()
            arm = {
                "req_per_s": round(len(toks) / elapsed_c, 3),
                "attainment": round(ev["attainment"], 6),
                "bubble_ratio": round(ev["bubble_ratio"], 6),
                "score": round(ev["score"], 6),
            }
            if controlled:
                verdicts: dict[str, int] = {}
                for dec in rep.get("decisions", []):
                    verdicts[dec["verdict"]] = verdicts.get(
                        dec["verdict"], 0) + 1
                arm.update(enabled=rep.get("enabled", False),
                           decisions=verdicts,
                           final_knobs=rep.get(
                               "knobs") and {k: v["value"]
                                             for k, v in rep["knobs"].items()},
                           oscillating=rep.get("oscillating"))
            else:
                # CONTROL_ENABLE=0 structural check: no controller object
                arm["enabled"] = rep.get("enabled", False)
            return arm, toks

        ctl: dict = {"trace": {
            "requests": len(c_trace), "phases": 3,
            "span_s": round(t_cursor, 2),
            "envelope": {"pipeline_depth": c_depth_max,
                         "prefill_batch": c_batch_max},
        }}
        try:
            tok_sets: dict[str, list] = {}
            statics: dict[str, dict] = {}
            for d_a in range(1, c_depth_max + 1):
                for b_a in range(1, c_batch_max + 1):
                    name = f"d{d_a}b{b_a}"
                    statics[name], tok_sets[name] = _run_ctl_arm(
                        d_a, b_a, False)
            ctl["static"] = statics
            ctl["controller"], tok_sets["controller"] = _run_ctl_arm(
                c_depth_max, c_batch_max, True)
            best_name = max(statics, key=lambda n: statics[n]["score"])
            best_score = statics[best_name]["score"]
            ctl["best_static"] = best_name
            ctl["tolerance"] = c_tol
            ctl["meets_statics"] = bool(
                ctl["controller"]["score"] >= best_score * (1.0 - c_tol))
            ref = tok_sets[f"d{c_depth_max}b{c_batch_max}"]
            ctl["token_exact"] = bool(
                all(t == ref for t in tok_sets.values()))
            # the ceiling static arm IS the CONTROL_ENABLE=0 engine at the
            # controller arm's boot config: identical tokens is the
            # off-path bit-identity evidence
            ctl["control_off_token_exact"] = bool(
                tok_sets["controller"] == ref)
            extra["controller"] = ctl
        except Exception as e:  # noqa: BLE001
            extra["controller"] = f"error: {e}"[:160]

    # KV-dtype three-way A/B (ISSUE 13): bf16 vs int8 vs int4 paged pools
    # under the same workload, archiving the decode-bandwidth story — pool
    # bytes per decode token (exact, from the pool planes), decode TPOT
    # percentiles, throughput, and per-arm mbu_decode_lb — plus the
    # correctness fields: every arm's tokens vs the bf16 arm (token_exact,
    # and parity = the fraction of requests matching exactly).
    if os.environ.get("GOFR_BENCH_KVDTYPE") == "1":
        from gofr_tpu.tpu.engine import GenerateEngine

        short = prompts[: max(4, n_requests // 4)]
        kvd: dict = {}
        arm_tokens: dict = {}
        for arm in ("bf16", "int8", "int4"):
            akw = dict(engine_kw(*best))
            akw.update(kv_layout="paged", page_size=akw.get("page_size", 128))
            akw.pop("kv_quantize", None)
            if arm != "bf16":
                akw["kv_quantize"] = arm
            cont_a = new_mock_container()  # isolated flight recorder per arm
            try:
                eng = GenerateEngine(llama, cfg, params, cont_a, **akw)
                try:
                    eng.warmup()
                    eng.start()
                    eng.generate(short[0], max_new_tokens=2, timeout=timeout)
                    kv_pool = eng.kv_cache
                    pool_positions = eng.total_pages * eng.page_size
                    kv_bytes_tok = (sum(x.nbytes for x in jax.tree.leaves(kv_pool))
                                    / pool_positions)
                    t0a = time.monotonic()
                    reqs = [eng.submit(p, max_new_tokens=max_new, timeout=timeout)
                            for p in short]
                    results = [r.result(timeout) for r in reqs]
                    el = time.monotonic() - t0a
                finally:
                    eng.stop()
                new_toks = sum(len(r["tokens"]) for r in results)
                ents = cont_a.flight.requests(limit=4 * len(short))
                tpots = [e["tpot_s"] for e in ents if e.get("tpot_s")]
                arm_tokens[arm] = [r["tokens"] for r in results]
                kvd[arm] = {
                    "req_per_s": round(len(short) / el, 3),
                    "decode_tokens_per_s": round(new_toks / el, 1),
                    "kv_bytes_per_decode_token": round(kv_bytes_tok, 2),
                    "tpot_p50_s": round(_percentile(tpots, 50), 5) if tpots else None,
                    "tpot_p99_s": round(_percentile(tpots, 99), 5) if tpots else None,
                    # shared estimator with THIS arm's exact pool width —
                    # the pre-perf-plane per-arm bound counted only weight
                    # bytes, so all three arms reported the SAME number and
                    # the A/B's entire point (the KV-plane width) was
                    # invisible in the utilization field
                    "mbu_decode_lb": (round(_perf.mbu_decode_lb(
                        weight_bytes=float(param_bytes), new_tokens=new_toks,
                        slots=int(best[0]), kv_bytes_per_pos=kv_bytes_tok,
                        hist_len=int(prompt_len), elapsed_s=el,
                        peak_bw=peaks[1]), 4) if peaks else None),
                    "mbu_decode_lb_params": (round(_perf.mbu_decode_lb_params(
                        weight_bytes=float(param_bytes), new_tokens=new_toks,
                        slots=int(best[0]), elapsed_s=el,
                        peak_bw=peaks[1]), 4) if peaks else None),
                }
            except Exception as e:  # noqa: BLE001
                kvd[arm] = f"error: {e}"[:200]
        ref_toks = arm_tokens.get("bf16")
        for arm in ("bf16", "int8", "int4"):
            if not isinstance(kvd.get(arm), dict):
                continue
            got = arm_tokens.get(arm)
            if ref_toks and got:
                matches = sum(a == b for a, b in zip(got, ref_toks))
                kvd[arm]["parity"] = round(matches / len(ref_toks), 3)
                kvd[arm]["token_exact"] = matches == len(ref_toks)
            else:
                kvd[arm]["parity"] = None
                kvd[arm]["token_exact"] = None
        extra["kvdtype"] = kvd

    # Tensor-parallel paged-pool A/B (ISSUE 19): replicated vs tp-sharded
    # pool on a forced multi-device host mesh (the CI job exports
    # XLA_FLAGS=--xla_force_host_platform_device_count=8; pin_cpu never
    # lowers an existing count). Self-contained arms on the tiny f32 config
    # — f32 keeps the argmax stable under the sharded o-projection reduce,
    # so token-exactness vs the single-device greedy reference is a hard
    # verdict, not a tolerance. Three claims: tokens exact on both arms,
    # per-device pool bytes ≈ 1/tp of replicated, and strictly more pool
    # pages per device at the replicated arm's per-device HBM budget.
    if os.environ.get("GOFR_BENCH_TP") == "1":
        from gofr_tpu.container import new_mock_container as _tp_container
        from gofr_tpu.models import ModelSpec as _TPSpec
        from gofr_tpu.testutil import greedy_reference as _tp_ref
        from gofr_tpu.testutil import tiny_f32_llama as _tp_tiny
        from gofr_tpu.tpu.engine import build_engine as _tp_build

        tp_mesh = os.environ.get("GOFR_BENCH_TP_MESH", "dp:2,tp:4")
        tp_size = 1
        for _part in tp_mesh.split(","):
            _ax, _, _n = _part.partition(":")
            tp_size = tp_size * int(_n or 1) if _ax.strip() == "tp" else tp_size
        needed = 1
        for _part in tp_mesh.split(","):
            needed *= int(_part.partition(":")[2] or 1)
        if len(jax.devices()) < needed:
            extra["tp"] = (f"skipped: mesh {tp_mesh!r} needs {needed} host "
                           f"devices, have {len(jax.devices())} (export XLA_"
                           f"FLAGS=--xla_force_host_platform_device_count={needed})")
        else:
            tcfg, tparams = _tp_tiny()
            tref = _tp_ref(tcfg, tparams)
            tp_new = 8
            tp_prompts = [[1 + (13 * i + j) % 200 for j in range(6 + i % 3)]
                          for i in range(6)]
            tp_want = [tref(p, tp_new) for p in tp_prompts]
            tp_arms: dict = {}
            for arm, shard in (("replicated", "off"), ("sharded", "tp")):
                ca = _tp_container({"TPU_MESH": tp_mesh,
                                    "ENGINE_KV_SHARD": shard})
                try:
                    eng = _tp_build(
                        _TPSpec(family="llama", task="generate", config=tcfg),
                        ca, seed=3, slots=4, max_len=64, max_prefill_batch=2,
                        kv_layout="paged", page_size=8)
                    try:
                        # per-device footprint at ALLOCATION time — the
                        # high-water mark capacity sizing must fit. The
                        # unsharded pool materializes whole on one device
                        # (GSPMD may opportunistically reshard it after the
                        # first donated step, but total_pages was already
                        # sized against full planes); the sharded pool is
                        # born 1/tp per device.
                        per_dev: dict = {}
                        for leaf in jax.tree.leaves(eng.kv_cache):
                            for sh in leaf.addressable_shards:
                                key = str(sh.device.id)
                                per_dev[key] = per_dev.get(key, 0) + sh.data.nbytes
                        t0a = time.monotonic()
                        reqs = [eng.submit(p, max_new_tokens=tp_new,
                                           timeout=timeout) for p in tp_prompts]
                        res = [r.result(timeout) for r in reqs]
                        el = time.monotonic() - t0a
                        stats = eng.page_pool_stats() or {}
                        tp_arms[arm] = {
                            "kv_shards": int(getattr(eng, "kv_shards", 1)),
                            "req_per_s": round(len(tp_prompts) / el, 3),
                            "pool_bytes_per_device": max(per_dev.values()),
                            "page_bytes_per_device": int(
                                stats.get("page_bytes_device", 0)),
                            "token_exact": [r["tokens"] for r in res] == tp_want,
                        }
                    finally:
                        eng.stop()
                except Exception as e:  # noqa: BLE001
                    tp_arms[arm] = f"error: {e}"[:200]
            tp_rec: dict = {"mesh": tp_mesh, "tp": tp_size, "arms": tp_arms}
            rep, shd = tp_arms.get("replicated"), tp_arms.get("sharded")
            if isinstance(rep, dict) and isinstance(shd, dict):
                ratio = (shd["pool_bytes_per_device"]
                         / max(1, rep["pool_bytes_per_device"]))
                budget = rep["pool_bytes_per_device"]
                pages_rep = budget // max(1, rep["page_bytes_per_device"])
                pages_shd = budget // max(1, shd["page_bytes_per_device"])
                tp_rec["verdicts"] = {
                    "token_exact": bool(rep["token_exact"]
                                        and shd["token_exact"]),
                    "device_bytes_ratio": round(ratio, 4),
                    # ≈ 1/tp with slack for the non-plane leaves (spec
                    # history stays replicated when enabled; none here)
                    "device_bytes_shrink_ok": ratio <= (1.0 / tp_size) * 1.25,
                    "max_pages_equal_budget": {
                        "replicated": int(pages_rep), "sharded": int(pages_shd),
                        "sharded_gt": bool(pages_shd > pages_rep),
                    },
                }
            extra["tp"] = tp_rec

    # Quality-plane drill (ISSUE 17). Clean arms: each KV dtype runs the
    # divergence shadow at rate 1.0 and must close with zero quality-SLO
    # breaches (bf16's serving arm IS the reference arm, so its top1
    # agreement is exactly 1.0 by construction — asserted by the CI
    # verdict). Chaos arm: the int8 engine is BUILT under
    # quality.corrupt (dequant-scale perturbation baked into the compiled
    # gather at trace time), which must drop top1 agreement, flip the
    # quality burn, write a capture bundle carrying the quality section,
    # and reproduce token-for-token through scripts/replay_bundle.py.
    if os.environ.get("GOFR_BENCH_QUALITY") == "1":
        import contextlib
        import glob
        import shutil

        from gofr_tpu.fleet import chaos as _chaos
        from gofr_tpu.tpu.engine import GenerateEngine

        qshort = prompts[: max(3, n_requests // 8)]
        q_new = min(max_new, 8)
        cap_dir = os.environ.get("GOFR_BENCH_QUALITY_DIR",
                                 "/tmp/gofr_bench_quality")
        shutil.rmtree(cap_dir, ignore_errors=True)
        # CHECK_INTERVAL 0: breach listeners fire synchronously on EVERY
        # observation — shadow samples finalize ms apart on the idle loop,
        # and a nonzero interval can swallow exactly the sample that
        # crosses min_samples, leaving a burn with no capture
        q_conf = {
            "SLO_DEFAULT_QUALITY": "0.99", "SLO_MIN_SAMPLES": "2",
            "SLO_BURN_THRESHOLD": "2", "SLO_CHECK_INTERVAL_S": "0",
            "SLO_CAPTURE": "true", "SLO_CAPTURE_DIR": cap_dir,
            "SLO_CAPTURE_MIN_INTERVAL_S": "0.01", "SLO_CAPTURE_BURST": "8",
        }

        def _quality_arm(kvq: str, corrupt: bool) -> dict:
            akw = dict(engine_kw(*best))
            akw.update(kv_layout="paged", page_size=akw.get("page_size", 128))
            akw.pop("kv_quantize", None)
            if kvq != "bf16":
                akw["kv_quantize"] = kvq
            akw.update(quality_shadow_rate=1.0,
                       quality_max_pending=len(qshort) + 4)
            if kvq == "int4" and not corrupt:
                # 4-bit KV error flips greedy ties on the tiny random-init
                # model (same caveat the kvdtype A/B documents for parity);
                # that is honest numerics, not an anomaly — don't let the
                # clean arm burn on it. The corrupt arm keeps the default
                # gate: chaos must push agreement well below any tie noise.
                akw["quality_top1_min"] = 0.75
            cont_q = new_mock_container(dict(q_conf))
            scope = (_chaos.override("quality.corrupt:drop,factor=8")
                     if corrupt else contextlib.nullcontext())
            with scope:
                eng = GenerateEngine(llama, cfg, params, cont_q, **akw)
                cont_q.register_engine("lm", eng)
                try:
                    eng.warmup()
                    eng.start()
                    reqs = [eng.submit(p, max_new_tokens=q_new, timeout=timeout)
                            for p in qshort]
                    for r in reqs:
                        r.result(timeout)
                    eng._quality.drain(timeout)
                    snap = eng.quality_snapshot()
                finally:
                    eng.stop()
            qbr = [b for b in cont_q.slo.breaches()
                   if b.get("objective") == "quality"]
            top1 = [e["report"]["top1_agree"] for e in snap.get("recent", [])]
            return {
                "samples": snap["samples"], "good": snap["good"],
                "errors": snap["errors"],
                "top1_agree_mean":
                    round(sum(top1) / len(top1), 4) if top1 else None,
                "top1_agree_min": round(min(top1), 4) if top1 else None,
                "quality_breaches": len(qbr),
                "burned": bool(qbr),
            }

        qual: dict = {}
        for arm in ("bf16", "int8", "int4"):
            try:
                qual[arm] = _quality_arm(arm, corrupt=False)
            except Exception as e:  # noqa: BLE001
                qual[arm] = f"error: {e}"[:200]
        try:
            corrupt = _quality_arm("int8", corrupt=True)
            bundles = sorted(glob.glob(os.path.join(cap_dir, "slo-capture-*")))
            corrupt["bundle"] = bundles[-1] if bundles else None
            if bundles:
                sys.path.insert(0, os.path.join(
                    os.path.dirname(os.path.abspath(__file__)), "scripts"))
                import replay_bundle as _rb
                # params= hands the replay the exact served tree; the CLI
                # default (llama.init at the recorded sampler seed) matches
                # it here anyway since the bench inits at key(0) with seed 0
                rep = _rb.replay(bundles[-1], run_engine=True, params=params,
                                 max_samples=2)
                corrupt["replay_reproduced"] = bool(rep["reproduced"])
            else:
                corrupt["replay_reproduced"] = False
            qual["corrupt_int8"] = corrupt
        except Exception as e:  # noqa: BLE001
            qual["corrupt_int8"] = f"error: {e}"[:200]
        extra["quality"] = qual

    # kernel A/B on the chip: engine throughput with the Pallas kernels
    # forced on vs off (fresh engines retrace under the env toggle)
    if os.environ.get("GOFR_BENCH_PALLAS_AB") == "1" and on_accel:
        short = prompts[: max(8, n_requests // 8)]
        ab: dict = {}
        for mode, env_val in (("xla", "0"), ("pallas", "1")):
            os.environ["GOFR_PALLAS"] = env_val
            try:
                r = _run_once(engine_kw(*best), cfg, params, container, llama,
                              short, max_new, timeout)
                ab[mode] = round(len(short) / r["elapsed"], 3)
            except Exception as e:  # noqa: BLE001
                ab[mode] = f"error: {e}"[:120]
        os.environ.pop("GOFR_PALLAS", None)
        extra["pallas_ab_req_per_s"] = ab
        if isinstance(ab.get("pallas"), float) and isinstance(ab.get("xla"), float):
            extra["pallas_speedup"] = round(ab["pallas"] / ab["xla"], 3)

    # vs_baseline is only meaningful against the north-star bar (125 req/s/chip
    # for one_b-class generate on TPU); a tiny-model CPU fallback could "beat"
    # it vacuously, so report null there rather than an inflated ratio.
    comparable = preset == "one_b" and on_accel
    vs_baseline = round(req_per_s / 125.0, 4) if comparable else None
    # Un-blinding (ROADMAP O3, ISSUE 11): BENCH_r04/r05 silently fell back
    # to CPU behind the probe timeout and archived "green" numbers. A
    # fallback the operator did not ask for is now a loud failure: the
    # archive says INVALID_CPU_FALLBACK and the process exits nonzero, so
    # no harness can mistake a CPU run for a TPU datapoint again. Asking
    # for CPU explicitly (GOFR_BENCH_PLATFORM=cpu, or the
    # GOFR_BENCH_ALLOW_CPU=1 escape hatch for CI smokes) stays a valid —
    # clearly-labelled — CPU run.
    silent_fallback = (backend_diag.startswith("TPU unavailable")
                      and os.environ.get("GOFR_BENCH_ALLOW_CPU") != "1")
    if silent_fallback:
        vs_baseline = "INVALID_CPU_FALLBACK"
        extra["platform_fallback"] = backend_diag
    print(json.dumps({
        "metric": f"llama_{preset}_generate_req_per_s_per_chip",
        "value": round(req_per_s, 3),
        "unit": "req/s",
        "vs_baseline": vs_baseline,
        "extra": extra,
    }))
    if silent_fallback:
        print("bench: FAILING LOUD — TPU probe fell back to CPU "
              f"({backend_diag}); these numbers are not a TPU datapoint. "
              "Set GOFR_BENCH_PLATFORM=cpu or GOFR_BENCH_ALLOW_CPU=1 to run "
              "an intentional CPU bench.", file=sys.stderr)
        sys.exit(3)


if __name__ == "__main__":
    main()
