"""Headline benchmark: flagship Llama generate throughput through the
continuous-batching engine (BASELINE.md config #2 analog on one chip).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "req/s", "vs_baseline": N}

``vs_baseline`` is value / 125 — the north-star target of ≥1000 req/s on a
v5e-8 (BASELINE.json) prorated to a single chip. The reference publishes
no numbers of its own (BASELINE.md), so the north-star target is the bar.

Env knobs:
    GOFR_BENCH_PRESET    one_b (default) | tiny  (tiny = CPU smoke test)
    GOFR_BENCH_REQUESTS  total requests (default 64)
    GOFR_BENCH_SLOTS     decode slots (default 16)
    GOFR_BENCH_PROMPT    prompt length (default 64)
    GOFR_BENCH_NEW       generated tokens per request (default 64)
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402
import numpy as np  # noqa: E402


def main() -> None:
    preset = os.environ.get("GOFR_BENCH_PRESET", "one_b")
    n_requests = int(os.environ.get("GOFR_BENCH_REQUESTS", "64"))
    slots = int(os.environ.get("GOFR_BENCH_SLOTS", "16"))
    prompt_len = int(os.environ.get("GOFR_BENCH_PROMPT", "64"))
    max_new = int(os.environ.get("GOFR_BENCH_NEW", "64"))

    from gofr_tpu.container import new_mock_container
    from gofr_tpu.models import LlamaConfig, llama
    from gofr_tpu.tpu.engine import GenerateEngine

    if preset == "tiny":
        cfg = LlamaConfig.tiny()
    else:
        cfg = LlamaConfig.one_b()

    container = new_mock_container()
    params = llama.init(cfg, jax.random.key(0))
    max_len = prompt_len + max_new + 8
    engine = GenerateEngine(
        llama, cfg, params, container,
        slots=slots, max_len=max_len,
        max_prefill_batch=4,
        prefill_buckets=[prompt_len],
    )
    engine.start()

    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, cfg.vocab_size, size=prompt_len).tolist() for _ in range(n_requests)]

    # warmup: compile prefill + decode programs
    engine.generate(prompts[0], max_new_tokens=2, timeout=600)

    results = [None] * n_requests
    errors: list[Exception] = []

    def worker(i: int) -> None:
        try:
            results[i] = engine.generate(prompts[i], max_new_tokens=max_new, timeout=1200)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    t0 = time.monotonic()
    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n_requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.monotonic() - t0
    engine.stop()

    if errors or any(r is None for r in results):
        print(json.dumps({"metric": "bench_error", "value": 0, "unit": "req/s",
                          "vs_baseline": 0, "error": str(errors[:1])}))
        sys.exit(1)

    total_tokens = sum(len(r["tokens"]) for r in results)
    req_per_s = n_requests / elapsed
    tok_per_s = total_tokens / elapsed
    platform = jax.devices()[0].platform

    print(json.dumps({
        "metric": f"llama_{preset}_generate_req_per_s_per_chip",
        "value": round(req_per_s, 3),
        "unit": "req/s",
        "vs_baseline": round(req_per_s / 125.0, 4),
        "extra": {
            "decode_tokens_per_s": round(tok_per_s, 1),
            "requests": n_requests,
            "prompt_len": prompt_len,
            "max_new_tokens": max_new,
            "slots": slots,
            "platform": platform,
            "elapsed_s": round(elapsed, 2),
        },
    }))


if __name__ == "__main__":
    main()
