"""Flagship TPU-serving example (reference has no model layer — this is the
new capability, SURVEY.md §2.9): a Llama generate endpoint behind the
continuous-batching engine, plus token streaming over websocket."""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))

import jax.numpy as jnp

from gofr_tpu import App
from gofr_tpu.config import EnvConfig
from gofr_tpu.models import LlamaConfig, ModelSpec


def build_app(config=None, *, preset: str = "tiny") -> App:
    import os

    folder = os.path.join(os.path.dirname(os.path.abspath(__file__)), "configs")
    app = App(config=config or EnvConfig(folder=folder))

    from gofr_tpu.utils import ByteTokenizer

    # vocab must cover the byte tokenizer's 259 ids; prompts can be raw
    # token-id lists OR strings (encoded through the tokenizer), and results
    # carry decoded text alongside ids. EOS is disabled here because random
    # weights emit any token — a real checkpoint would keep the tokenizer's
    # eos_token_id (build_engine wires it automatically).
    cfg = LlamaConfig.tiny(vocab_size=300) if preset == "tiny" else LlamaConfig.one_b()
    dtype = jnp.float32 if preset == "tiny" else jnp.bfloat16
    spec = ModelSpec("llama", cfg, task="generate", dtype=dtype, tokenizer=ByteTokenizer())
    app.serve_model("lm", spec, slots=4, max_len=64, eos_token_id=-1)

    async def generate(ctx):
        # async handler + agenerate: awaits the engine future on the event
        # loop instead of parking a handler thread per in-flight request
        body = ctx.bind(dict)
        return await ctx.agenerate(
            "lm", body["prompt"],
            max_new_tokens=int(body.get("max_new_tokens", 8)),
            temperature=float(body.get("temperature", 0.0)),
            timeout=body.get("timeout", 120),
        )

    def generate_stream(ctx):
        """SSE: tokens arrive as `data:` events while decode is running."""
        from gofr_tpu.http.streaming import StreamingResponse

        body = ctx.bind(dict)
        it = ctx.generate(
            "lm", body["prompt"],
            max_new_tokens=int(body.get("max_new_tokens", 8)),
            temperature=float(body.get("temperature", 0.0)),
            timeout=body.get("timeout", 120),
            stream=True,
        )
        return StreamingResponse(it, event="token")

    def ws_generate(ctx):
        """Websocket: one message per token (websocket.go:37-53 parity)."""
        from gofr_tpu.http.streaming import StreamingResponse

        body = ctx.bind(dict)
        it = ctx.generate(
            "lm", body["prompt"],
            max_new_tokens=int(body.get("max_new_tokens", 8)),
            timeout=body.get("timeout", 120),
            stream=True,
        )
        return StreamingResponse(it)

    app.post("/generate", generate)
    app.post("/generate/stream", generate_stream)
    app.websocket("/ws/generate", ws_generate)
    return app


if __name__ == "__main__":
    build_app().run()
