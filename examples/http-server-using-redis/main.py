"""Redis-backed HTTP server (reference `examples/http-server-using-redis`):
SET/GET/pipeline against the container's Redis datasource — the from-scratch
RESP wire client (`gofr_tpu/datasource/redis.py`), wired only when
REDIS_HOST is configured (`container.go:91` semantics).
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))

from gofr_tpu import App
from gofr_tpu.config import EnvConfig
from gofr_tpu.http.errors import EntityNotFound

REDIS_EXPIRY_S = 5 * 60


def build_app(config=None) -> App:
    import os

    folder = os.path.join(os.path.dirname(os.path.abspath(__file__)), "configs")
    app = App(config=config or EnvConfig(folder=folder))

    def redis_set(ctx):
        body = ctx.bind(dict)
        for key, value in body.items():
            ctx.redis.set(key, value, ex=REDIS_EXPIRY_S)
        return "Successful"

    def redis_get(ctx):
        key = ctx.path_param("key")
        value = ctx.redis.get(key)
        if value is None:
            raise EntityNotFound(f"key {key!r}")
        return value.decode() if isinstance(value, bytes) else value

    def redis_pipeline(ctx):
        results = (
            ctx.redis.pipeline()
            .command("SET", "pipe-key", "pipe-value", "EX", REDIS_EXPIRY_S)
            .command("GET", "pipe-key")
            .execute()
        )
        return [r.decode() if isinstance(r, bytes) else r for r in results]

    app.post("/redis", redis_set)
    app.get("/redis/{key}", redis_get)
    app.get("/redis-pipeline", redis_pipeline)
    return app


if __name__ == "__main__":
    build_app().run()
