"""Multipart file binding (reference `examples/using-file-bind`): a POST
/upload route binding a multipart form into a dataclass — a plain form
field, a generic uploaded file, and a zip archive expanded in memory
(`pkg/gofr/http/multipart_file_bind.go` + `pkg/gofr/file/zip.go` parity).
"""

import os as _os
import sys as _sys
from dataclasses import dataclass, field

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))

from gofr_tpu import App
from gofr_tpu.config import EnvConfig
from gofr_tpu.http.multipart import UploadFile, Zip


@dataclass
class Data:
    name: str = ""
    # zip archive under form key "upload", expanded in memory
    upload: Zip = field(default_factory=Zip)
    # generic file under form key "a"
    a: UploadFile | None = None


def build_app(config=None) -> App:
    import os

    folder = os.path.join(os.path.dirname(os.path.abspath(__file__)), "configs")
    app = App(config=config or EnvConfig(folder=folder))

    def upload(ctx):
        d = ctx.bind(Data)
        return {
            "name": d.name,
            "zip_files": sorted(d.upload.files),
            "zip_bytes": sum(len(v) for v in d.upload.files.values()),
            "file": None if d.a is None else
            {"filename": d.a.filename, "size": len(d.a.content)},
        }

    app.post("/upload", upload)
    return app


if __name__ == "__main__":
    build_app().run()
