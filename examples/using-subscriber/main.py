"""Subscriber half of the two-process pub/sub pair (reference
`examples/using-subscriber`): consumes orders published by the separate
`examples/using-publisher` process over the shared file-transport broker
(real Kafka when PUBSUB_BACKEND=kafka), with at-least-once commit
semantics and an idempotent handler — the consumer-side discipline that
turns redelivery into an exactly-once EFFECT.

GET /processed exposes what this process consumed, so the publisher
process (and the example-tier test) can verify cross-process delivery
over plain HTTP."""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))

from gofr_tpu import App
from gofr_tpu.config import EnvConfig

PROCESSED: list[dict] = []
_SEEN: set = set()


def build_app(config=None) -> App:
    import os

    folder = os.path.join(os.path.dirname(os.path.abspath(__file__)), "configs")
    app = App(config=config or EnvConfig(folder=folder))

    def consume_order(ctx):
        order = ctx.bind(dict)
        # idempotency key: redelivery after a crash-before-commit must not
        # double-apply the order (at-least-once delivery, exactly-once effect)
        key = order.get("id")
        if key is not None and key in _SEEN:
            ctx.logger.info(f"duplicate delivery of order {key} ignored")
            return None  # still commits: the effect is already applied
        if key is not None:
            _SEEN.add(key)
        PROCESSED.append(order)
        ctx.logger.info(f"processed order {order}")
        return None  # success → offset committed (at-least-once)

    def processed(_ctx):
        return PROCESSED

    app.subscribe("orders", consume_order)
    app.get("/processed", processed)
    return app


if __name__ == "__main__":
    build_app().run()
