"""gRPC service example (reference `examples/grpc-server`): a Hello service
served through the framework's gRPC server, so every RPC gets the
recovery + span + RPCLog interceptor chain (`pkg/gofr/grpc.go:22-27`
parity) and — unlike the reference, whose gRPC handlers never see the
framework context (SURVEY §3.3) — can reach datasources via
``current_grpc_context()``.

The wire format here is JSON-over-gRPC via generic method handlers, so the
example runs without protoc-generated stubs; generated servicers register
through the same ``app.register_grpc_service(add_fn, servicer)`` call.
"""

import json
import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))

import grpc

from gofr_tpu import App
from gofr_tpu.config import EnvConfig
from gofr_tpu.grpc.server import current_grpc_context

SERVICE = "hello.Hello"


class HelloServicer:
    def SayHello(self, request: dict, context) -> dict:
        ctx = current_grpc_context()
        if ctx is not None:
            ctx.logger.infof("SayHello from %s", request.get("name", "?"))
        name = request.get("name") or "World"
        return {"message": f"Hello {name}!"}

    def Boom(self, request: dict, context) -> dict:
        raise RuntimeError("intentional panic — recovered by the interceptor")

    def Countdown(self, request: dict, context):
        """Server-streaming RPC — also wrapped by the interceptor chain
        (unlike the reference, which intercepts only unary RPCs)."""
        n = int(request.get("from", 3))
        if n > 100:
            raise ValueError("countdown too long")
        for i in range(n, 0, -1):
            yield {"tick": i}


def add_hello_to_server(servicer: HelloServicer, server: grpc.Server) -> None:
    """Hand-rolled equivalent of a generated ``add_*_to_server``."""
    handlers = {
        "SayHello": grpc.unary_unary_rpc_method_handler(
            servicer.SayHello,
            request_deserializer=lambda b: json.loads(b.decode() or "{}"),
            response_serializer=lambda o: json.dumps(o).encode(),
        ),
        "Boom": grpc.unary_unary_rpc_method_handler(
            servicer.Boom,
            request_deserializer=lambda b: json.loads(b.decode() or "{}"),
            response_serializer=lambda o: json.dumps(o).encode(),
        ),
        "Countdown": grpc.unary_stream_rpc_method_handler(
            servicer.Countdown,
            request_deserializer=lambda b: json.loads(b.decode() or "{}"),
            response_serializer=lambda o: json.dumps(o).encode(),
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),)
    )


def build_app(config=None) -> App:
    import os

    folder = os.path.join(os.path.dirname(os.path.abspath(__file__)), "configs")
    app = App(config=config or EnvConfig(folder=folder))
    app.register_grpc_service(add_hello_to_server, HelloServicer())
    return app


if __name__ == "__main__":
    build_app().run()
