"""Custom application metrics (reference `examples/using-custom-metrics`):
an e-commerce store registering its own counter / up-down counter / gauge /
histogram alongside the framework metrics, recorded from handlers and
scraped from the separate metrics port.
"""

import os as _os
import sys as _sys
import time

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))

from gofr_tpu import App
from gofr_tpu.config import EnvConfig

TRANSACTION_SUCCESS = "transaction_success"
TRANSACTION_TIME = "transaction_time"
TOTAL_CREDIT_DAY_SALES = "total_credit_day_sale"
PRODUCT_STOCK = "product_stock"


def build_app(config=None) -> App:
    import os

    folder = os.path.join(os.path.dirname(os.path.abspath(__file__)), "configs")
    app = App(config=config or EnvConfig(folder=folder))
    m = app.container.metrics

    m.new_counter(TRANSACTION_SUCCESS, "count of successful transactions")
    m.new_updown_counter(TOTAL_CREDIT_DAY_SALES, "total credit sales in a day")
    m.new_gauge(PRODUCT_STOCK, "number of products in stock")
    m.new_histogram(TRANSACTION_TIME, "time taken by a transaction (ms)",
                    buckets=[5, 10, 15, 20, 25, 35])

    def transaction(ctx):
        start = time.monotonic()
        # ... transaction logic ...
        ctx.metrics.increment_counter(TRANSACTION_SUCCESS)
        ctx.metrics.record_histogram(TRANSACTION_TIME, (time.monotonic() - start) * 1e3)
        ctx.metrics.delta_updown_counter(TOTAL_CREDIT_DAY_SALES, 1000, sale_type="credit")
        ctx.metrics.set_gauge(PRODUCT_STOCK, 10)
        return "Transaction Successful"

    def sale_return(ctx):
        ctx.metrics.delta_updown_counter(TOTAL_CREDIT_DAY_SALES, -1000, sale_type="credit_return")
        ctx.metrics.set_gauge(PRODUCT_STOCK, 50)
        return "Return Successful"

    app.post("/transaction", transaction)
    app.post("/return", sale_return)
    return app


if __name__ == "__main__":
    build_app().run()
