"""WebSocket example (reference `examples/using-web-socket`): per-message
handler loop; bind() reads one message, return value is written back."""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))

from gofr_tpu import App
from gofr_tpu.config import EnvConfig


def build_app(config=None) -> App:
    import os

    folder = os.path.join(os.path.dirname(os.path.abspath(__file__)), "configs")
    app = App(config=config or EnvConfig(folder=folder))

    def echo(ctx):
        msg = ctx.bind(dict)
        return {"echo": msg, "via": "gofr-tpu"}

    app.websocket("/ws", echo)
    return app


if __name__ == "__main__":
    build_app().run()
