"""CLI example (reference `examples/sample-cmd`): subcommand routing, flag
binding into dataclasses, help generation."""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))

from dataclasses import dataclass

from gofr_tpu import new_cmd


@dataclass
class HelloParams:
    name: str = "World"
    shout: bool = False


def build_app():
    import os

    folder = os.path.join(os.path.dirname(os.path.abspath(__file__)), "configs")
    app = new_cmd(config_folder=folder)

    def hello(ctx):
        p = ctx.bind(HelloParams)
        msg = f"Hello {p.name}!"
        return msg.upper() if p.shout else msg

    def version(ctx):
        return "sample-cmd 1.0.0"

    app.sub_command("hello", hello, description="Greet someone (-name=X -shout)")
    app.sub_command("version", version, description="Print the version")
    return app


if __name__ == "__main__":
    build_app().run()
