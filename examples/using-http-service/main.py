"""Inter-service HTTP client example (reference `examples/using-http-service`):
a registered downstream service with circuit breaker + retry options."""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))

from gofr_tpu import App
from gofr_tpu.config import EnvConfig
from gofr_tpu.service import CircuitBreaker, Retry


def build_app(downstream_url: str, config=None) -> App:
    import os

    folder = os.path.join(os.path.dirname(os.path.abspath(__file__)), "configs")
    app = App(config=config or EnvConfig(folder=folder))
    app.register_service(
        "catalog", downstream_url,
        CircuitBreaker(threshold=3, interval=0.2),
        Retry(max_retries=2),
    )

    def fetch(ctx):
        resp = ctx.http_service("catalog").get("item")
        return {"downstream": resp.json(), "status": resp.status_code}

    app.get("/fetch", fetch)
    return app


if __name__ == "__main__":
    import sys

    build_app(sys.argv[1] if len(sys.argv) > 1 else "http://localhost:9000").run()
