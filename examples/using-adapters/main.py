"""Multi-LoRA adapter multiplexing (docs/serving.md): one engine, many
tenants' adapters. Requests name an adapter via the ``adapter_id`` body
field (or the ``X-Adapter-ID`` header — the router also keys ring
affinity on it); lanes with no adapter serve the base model bit-exactly.
All adapters co-batch into the same decode steps, and the perf plane
attributes MFU/MBU and device-seconds per adapter — the per-tenant COGS
meter:

    python examples/using-adapters/main.py &
    curl -s -X POST :8819/generate \
      -d '{"prompt": [1,2,3], "max_new_tokens": 8}'                # base
    curl -s -X POST :8819/generate \
      -d '{"prompt": [1,2,3], "max_new_tokens": 8, "adapter_id": "fr"}'
    curl -s -X POST :8819/generate -H 'X-Adapter-ID: de' \
      -d '{"prompt": [1,2,3], "max_new_tokens": 8}'
    curl -s :8819/adapters                                # both tiers' stats
    curl -s :9819/metrics | grep app_tpu_adapter_         # per-tenant meter
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))

import jax.numpy as jnp

from gofr_tpu import App
from gofr_tpu.config import EnvConfig
from gofr_tpu.models import LlamaConfig, ModelSpec


def build_app(config=None) -> App:
    import os

    folder = os.path.join(os.path.dirname(os.path.abspath(__file__)), "configs")
    app = App(config=config or EnvConfig(folder=folder))

    from gofr_tpu.adapters import random_adapter
    from gofr_tpu.utils import ByteTokenizer

    cfg = LlamaConfig.tiny(vocab_size=300)
    spec = ModelSpec("llama", cfg, task="generate", dtype=jnp.float32,
                     tokenizer=ByteTokenizer())
    # ADAPTER_SLOTS=4 in configs/.env builds the adapter plane; passing
    # adapter_slots=4 here would be the programmatic equivalent
    engine = app.serve_model("lm", spec, slots=4, max_len=64, eos_token_id=-1)

    # two tenants' adapters — in production these come from fine-tune
    # checkpoints; random factors keep the example self-contained. Each
    # can carry its own QoS class and per-tenant concurrency cap.
    engine.register_adapter(random_adapter(
        "fr", cfg.hidden_size, cfg.vocab_size, rank=4, seed=1))
    engine.register_adapter(random_adapter(
        "de", cfg.hidden_size, cfg.vocab_size, rank=8, seed=2,
        max_concurrency=8))

    async def generate(ctx):
        from gofr_tpu.http.errors import InvalidParam

        body = ctx.bind(dict)
        kw = {}
        if body.get("adapter_id"):
            # the context middleware also picks up X-Adapter-ID; the body
            # field is the explicit spelling
            kw["adapter_id"] = body["adapter_id"]
        try:
            return await ctx.agenerate(
                "lm", body["prompt"],
                max_new_tokens=int(body.get("max_new_tokens", 8)), **kw)
        except ValueError as e:
            # "unknown adapter ..." is the caller's mistake, not ours
            raise InvalidParam("adapter_id") from e

    async def adapters(ctx):
        # both tiers' occupancy + the live base-weight epoch
        return engine.adapter_stats()

    app.post("/generate", generate)
    app.get("/adapters", adapters)
    return app


if __name__ == "__main__":
    build_app().run()
