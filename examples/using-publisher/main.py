"""Publisher half of the two-process pub/sub pair (reference
`examples/using-publisher`): an HTTP handler publishes orders onto the
broker; the separate `examples/using-subscriber` process consumes them.

The default transport is the in-tree FILE broker (PUBSUB_BACKEND=file):
both processes share the append-only log under PUBSUB_DIR, so the pair
runs with zero external dependencies. Point PUBSUB_BACKEND=kafka (+
PUBSUB_BROKER) at a real broker to run the same code against Kafka."""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))

from gofr_tpu import App
from gofr_tpu.config import EnvConfig


def build_app(config=None) -> App:
    import os

    folder = os.path.join(os.path.dirname(os.path.abspath(__file__)), "configs")
    app = App(config=config or EnvConfig(folder=folder))

    def publish_order(ctx):
        order = ctx.bind(dict)
        ctx.publish("orders", order)
        return {"published": True, "order": order}

    app.post("/order", publish_order)
    return app


if __name__ == "__main__":
    build_app().run()
