"""QoS-guarded model serving (docs/qos.md): the serving-llm app with the
full overload story wired on — priority classes via the X-QoS-Class
header, per-API-key rate limits, backlog shedding, and deadline-aware
admission. Flood it and watch 429/503 + Retry-After instead of timeouts:

    python examples/using-qos/main.py &
    for i in $(seq 20); do
      curl -s -o /dev/null -w '%{http_code} ' -X POST :8816/generate \
        -H 'X-QoS-Class: batch' -d '{"prompt": [1,2,3], "max_new_tokens": 24}'
    done; echo
    curl -s -X POST :8816/generate -H 'X-QoS-Class: interactive' \
      -d '{"prompt": "hi", "max_new_tokens": 4, "timeout": 10}'
    curl -s :9816/metrics | grep app_qos_
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))

import jax.numpy as jnp

from gofr_tpu import App
from gofr_tpu.config import EnvConfig
from gofr_tpu.models import LlamaConfig, ModelSpec


def build_app(config=None) -> App:
    import os

    folder = os.path.join(os.path.dirname(os.path.abspath(__file__)), "configs")
    app = App(config=config or EnvConfig(folder=folder))
    # QOS_ENABLED=true in configs/.env already enabled QoS from config;
    # enable_qos(...) here would be the programmatic equivalent.

    from gofr_tpu.utils import ByteTokenizer

    cfg = LlamaConfig.tiny(vocab_size=300)
    spec = ModelSpec("llama", cfg, task="generate", dtype=jnp.float32,
                     tokenizer=ByteTokenizer())
    app.serve_model("lm", spec, slots=4, max_len=64, eos_token_id=-1)

    async def generate(ctx):
        # the middleware classified the request from X-QoS-Class; passing a
        # `timeout` arms the deadline-feasibility gate (reject-not-queue)
        body = ctx.bind(dict)
        return await ctx.agenerate(
            "lm", body["prompt"],
            max_new_tokens=int(body.get("max_new_tokens", 8)),
            timeout=body.get("timeout", 120),
        )

    app.post("/generate", generate)
    return app


if __name__ == "__main__":
    build_app().run()
