"""Publisher + subscriber example (reference `examples/using-publisher` +
`using-subscriber`): HTTP handler publishes orders; a subscription handler
consumes them with at-least-once commit semantics."""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))

from gofr_tpu import App
from gofr_tpu.config import EnvConfig

PROCESSED: list[dict] = []


def build_app(config=None) -> App:
    import os

    folder = os.path.join(os.path.dirname(os.path.abspath(__file__)), "configs")
    app = App(config=config or EnvConfig(folder=folder))

    def publish_order(ctx):
        order = ctx.bind(dict)
        ctx.publish("orders", order)
        return {"published": True}

    def consume_order(ctx):
        order = ctx.bind(dict)
        PROCESSED.append(order)
        ctx.logger.info(f"processed order {order}")
        return None  # success → offset committed (at-least-once)

    app.post("/order", publish_order)
    app.subscribe("orders", consume_order)
    return app


if __name__ == "__main__":
    build_app().run()
