"""Cron example (reference `examples/using-cron-jobs`): a 5-field schedule
firing a handler with a fresh traced Context."""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))

from gofr_tpu import App
from gofr_tpu.config import EnvConfig

RUNS: list[float] = []


def build_app(config=None) -> App:
    import os
    import time

    folder = os.path.join(os.path.dirname(os.path.abspath(__file__)), "configs")
    app = App(config=config or EnvConfig(folder=folder))

    def beat(ctx):
        RUNS.append(time.time())
        ctx.logger.info(f"cron beat #{len(RUNS)}")

    app.add_cron_job("* * * * *", "heartbeat", beat)
    return app


if __name__ == "__main__":
    build_app().run()
