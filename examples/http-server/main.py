"""Minimal HTTP app (reference `examples/http-server` analog): routes,
path/query params, KV-backed storage, error mapping, health endpoints."""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))

from dataclasses import dataclass

from gofr_tpu import App
from gofr_tpu.config import EnvConfig
from gofr_tpu.http.errors import EntityNotFound


@dataclass
class Person:
    name: str
    age: int


def build_app(config=None) -> App:
    import os

    folder = os.path.join(os.path.dirname(os.path.abspath(__file__)), "configs")
    app = App(config=config or EnvConfig(folder=folder))

    def greet(ctx):
        name = ctx.param("name") or "World"
        return f"Hello {name}!"

    def save(ctx):
        import json

        p = ctx.bind(Person)
        ctx.kv.set(f"person:{p.name}", json.dumps({"name": p.name, "age": p.age}))
        return {"saved": p.name}

    def load(ctx):
        name = ctx.path_param("name")
        got = ctx.kv.get(f"person:{name}")
        if got is None:
            raise EntityNotFound(f"person {name!r}")
        import json

        return json.loads(got)

    app.get("/greet", greet)
    app.post("/person", save)
    app.get("/person/{name}", load)
    return app


if __name__ == "__main__":
    build_app().run()
