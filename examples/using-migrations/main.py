"""Migrations example (reference `examples/using-migrations`): versioned,
transactional schema evolution recorded in gofr_migrations."""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))

from gofr_tpu import App
from gofr_tpu.config import EnvConfig
from gofr_tpu.migration import Migration


def all_migrations():
    def create_users(ds):
        ds.sql.execute("CREATE TABLE users (id INTEGER PRIMARY KEY, name TEXT)")

    def add_email(ds):
        ds.sql.execute("ALTER TABLE users ADD COLUMN email TEXT")

    return {
        20240101_00_00: Migration(up=create_users),
        20240201_00_00: Migration(up=add_email),
    }


def build_app(config=None) -> App:
    import os

    folder = os.path.join(os.path.dirname(os.path.abspath(__file__)), "configs")
    app = App(config=config or EnvConfig(folder=folder))
    app.migrate(all_migrations())

    def add_user(ctx):
        body = ctx.bind(dict)
        ctx.sql.execute("INSERT INTO users (name, email) VALUES (?, ?)",
                        (body["name"], body.get("email")))
        return {"ok": True}

    def list_users(ctx):
        return ctx.sql.query("SELECT name, email FROM users")

    app.post("/user", add_user)
    app.get("/user", list_users)
    return app


if __name__ == "__main__":
    build_app().run()
