"""CRUD generator example (reference `examples/using-add-rest-handlers`):
a dataclass reflected into POST/GET/GET-all/PUT/DELETE with SQL storage."""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))

from dataclasses import dataclass

from gofr_tpu import App
from gofr_tpu.config import EnvConfig


@dataclass
class Book:
    id: int
    title: str
    year: int


def build_app(config=None) -> App:
    import os

    folder = os.path.join(os.path.dirname(os.path.abspath(__file__)), "configs")
    app = App(config=config or EnvConfig(folder=folder))
    app.container.sql.execute(
        "CREATE TABLE IF NOT EXISTS book (id INTEGER PRIMARY KEY, title TEXT, year INTEGER)"
    )
    app.add_rest_handlers(Book)
    return app


if __name__ == "__main__":
    build_app().run()
