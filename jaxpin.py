"""The single home for the pin-to-CPU-backend discipline.

Used by tests/conftest.py, bench.py, and __graft_entry__.py — keep it
import-light (no package imports) so it can run before anything touches jax.

Image-specific constraints this encodes (see .claude/skills/verify/SKILL.md):
- The sitecustomize/axon hook imports jax at interpreter startup. Backend
  REGISTRATION happens then; INITIALIZATION happens at first device touch and
  can hang indefinitely when the TPU tunnel is down.
- ``jax.config.update("jax_platforms", "cpu")`` after import reliably avoids
  TPU init. Setting ``JAX_PLATFORMS=cpu`` in the env of a NEW process instead
  makes sitecustomize block at startup — never export it to children; strip
  it from child envs and have the child call :func:`pin_cpu` itself.
"""

from __future__ import annotations

import os
import re


def pin_cpu(n_devices: int = 1) -> None:
    """Force the CPU backend with >= n_devices virtual chips for THIS process.

    Safe to call repeatedly and after other jax imports, as long as no
    backend has been initialized yet. XLA_FLAGS must be set before the CPU
    client is created; an existing device-count flag is raised, never lowered.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    count = max(n_devices, int(m.group(1)) if m else 0)
    want = f"--xla_force_host_platform_device_count={count}"
    flags = flags.replace(m.group(0), want) if m else (flags + " " + want).strip()
    os.environ["XLA_FLAGS"] = flags

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # a backend is already initialized; devices("cpu") still works


def child_env(base: dict | None = None) -> dict:
    """A copy of the environment safe for spawning python children: drops
    JAX_PLATFORMS so the child's sitecustomize import cannot block."""
    env = dict(os.environ if base is None else base)
    env.pop("JAX_PLATFORMS", None)
    return env
