"""Deterministic offline replay of an SLO anomaly capture bundle's quality
section (docs/observability.md "Quality plane" runbook).

A quality burn at 3am leaves behind a ``slo-capture-*/bundle.json`` whose
``quality`` section carries, per engine, the complete deterministic input
set for every recently shadow-scored request: prompt token ids, emitted
tokens, the divergence report, plus a ``replay`` config (model family +
config, sampler seed, the engine knobs that shape compiled programs, the
armed chaos spec, adapter digest, weights epoch, fingerprint). This script
re-executes those samples on a cold process and diffs token-by-token:

1. **Serving re-execution** (default): rebuild the EXACT engine — same
   knobs, same sampler seed, same ``GOFR_CHAOS`` spec re-armed via
   ``chaos.override`` (trace-time corruption bakes back into the compiled
   program) — and greedily re-generate each sample's prompt. The emitted
   tokens must match the recorded ones position-by-position; a mismatch
   means the recorded state is incomplete, not that the bug is gone.
2. **Reference re-score**: teacher-force ``prompt + emitted`` through the
   golden configuration (dense KV, base weights) and the serving-numerics
   arm, and recompute the divergence report. The per-token agreement mask
   must reproduce the recorded one exactly — same first-divergence index,
   same disagreeing positions.

A sample "reproduces" when both hold; the exit code is 0 only when every
replayed sample reproduces. Weights come from ``llama.init`` at the
recorded seed (the engines' own convention); a checkpoint-serving fleet
must restore the recorded ``weights_epoch``'s checkpoint into the hot-swap
dir before replaying.

Usage:
    python scripts/replay_bundle.py /path/to/slo-capture-20260807-031502-001
    python scripts/replay_bundle.py bundle.json --no-engine --json
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
from typing import Any

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load_bundle(path: str) -> dict:
    if os.path.isdir(path):
        path = os.path.join(path, "bundle.json")
    with open(path) as f:
        return json.load(f)


def _build_model(replay: dict, *, params=None, init_seed: int | None = None):
    """(family module, cfg, params) from a recorded replay config.
    ``params`` short-circuits weight reconstruction (the importable-API
    path: a test or operator hands over the exact served tree); otherwise
    weights are ``llama.init`` at ``init_seed`` (default: the recorded
    sampler seed, the convention bench/test engines follow)."""
    import jax
    import jax.numpy as jnp

    name = str(replay.get("family", "llama"))
    if name != "llama":
        raise SystemExit(f"replay supports the llama family only (got {name!r})")
    from gofr_tpu.models import llama

    cfg_d = dict(replay["config"])
    dt = cfg_d.get("dtype")
    if isinstance(dt, str):
        cfg_d["dtype"] = jnp.dtype(dt).type
    cfg = llama.LlamaConfig(**cfg_d)
    if params is None:
        seed = int(replay.get("seed", 0)) if init_seed is None else int(init_seed)
        params = llama.init(cfg, jax.random.key(seed))
    return llama, cfg, params


def _chaos_scope(replay: dict):
    """Re-arm the chaos spec recorded at capture time — the corruption under
    test is part of the deterministic repro, not something to replay around."""
    spec = str(replay.get("chaos", "") or "")
    if not spec:
        return contextlib.nullcontext()
    from gofr_tpu.fleet import chaos

    return chaos.override(spec, seed=int(os.environ.get("GOFR_CHAOS_SEED", "0")))


def _replay_engine(family, cfg, params, replay: dict,
                   samples: list[dict]) -> list[dict]:
    """Serving re-execution: same engine knobs + seed + chaos spec, greedy
    re-generation of each sample's prompt, token-by-token diff."""
    from gofr_tpu.container import new_mock_container
    from gofr_tpu.tpu.engine import GenerateEngine

    ek = dict(replay.get("engine", {}))
    out: list[dict] = []
    with _chaos_scope(replay):
        container = new_mock_container({})
        engine = GenerateEngine(
            family, cfg, params, container,
            slots=int(ek.get("slots", 8)),
            max_len=int(ek.get("max_len", cfg.max_seq_len)),
            decode_chunk=int(ek.get("decode_chunk", 8)),
            kv_layout=str(ek.get("kv_layout", "slot")),
            page_size=int(ek.get("page_size", 128) or 128),
            total_pages=int(ek.get("total_pages", 0)) or None,
            spec_tokens=int(ek.get("spec_tokens", 0)),
            kv_quantize=str(ek.get("kv_quantize", "")),
            top_k=int(ek.get("top_k", 0)),
            top_p=float(ek.get("top_p", 1.0)),
            seed=int(replay.get("seed", 0)),
        )
        engine.start()
        try:
            for s in samples:
                want = [int(t) for t in s["emitted"]]
                res = engine.generate(s["prompt"],
                                      max_new_tokens=max(len(want), 1),
                                      temperature=0.0, timeout=120.0)
                got = [int(t) for t in res["tokens"]][: len(want)]
                first = next((i for i, (a, b) in enumerate(zip(got, want))
                              if a != b), -1)
                out.append({
                    "tokens_match": got == want,
                    "first_token_mismatch": first,
                    "replayed_tokens": got,
                })
        finally:
            engine.stop()
    return out


def _rescore(family, cfg, params, kv_dtype: str, sample: dict) -> dict:
    """Reference + serving-numerics teacher-forced re-score; the recomputed
    divergence report must reproduce the recorded per-token agreement."""
    from gofr_tpu.metrics.quality import (
        divergence_report, make_serving_attn_fn, teacher_forced_rows)

    serving_rows = teacher_forced_rows(
        family, cfg, params, sample["prompt"], sample["emitted"],
        attn_fn=make_serving_attn_fn(kv_dtype))
    ref_rows = teacher_forced_rows(
        family, cfg, params, sample["prompt"], sample["emitted"])
    return divergence_report(serving_rows, ref_rows, sample["emitted"])


def replay(bundle_path: str, *, run_engine: bool = True,
           max_samples: int = 0, params=None,
           init_seed: int | None = None) -> dict:
    """Replay every quality sample in a bundle; importable for tests.
    Returns {engine: {samples: [...], reproduced: bool}, "reproduced": bool}."""
    bundle = _load_bundle(bundle_path)
    quality = bundle.get("quality") or {}
    if not quality:
        raise SystemExit(f"{bundle_path}: bundle has no quality section "
                         "(was QUALITY_SHADOW_RATE > 0 when it was written?)")
    result: dict[str, Any] = {"engines": {}, "reproduced": True}
    for engine_name, snap in quality.items():
        replay_cfg = snap.get("replay") or {}
        samples = [s for s in snap.get("recent", []) if s.get("report")]
        if max_samples > 0:
            samples = samples[:max_samples]
        if not samples:
            continue
        family, cfg, eng_params = _build_model(
            replay_cfg, params=params, init_seed=init_seed)
        kv_dtype = str(snap.get("kv_dtype", "bf16"))
        engine_runs = (_replay_engine(family, cfg, eng_params, replay_cfg, samples)
                       if run_engine else [None] * len(samples))
        rows = []
        for sample, run in zip(samples, engine_runs):
            recorded = sample["report"]
            rescored = _rescore(family, cfg, eng_params, kv_dtype, sample)
            divergence_match = (
                rescored["agree"] == recorded.get("agree")
                and rescored["first_divergence"] == recorded.get("first_divergence"))
            row = {
                "request_id": sample.get("request_id"),
                "adapter": sample.get("adapter"),
                "tokens": len(sample["emitted"]),
                "recorded": recorded,
                "rescored": rescored,
                "divergence_match": divergence_match,
                "reproduced": divergence_match,
            }
            if run is not None:
                row.update(run)
                row["reproduced"] = divergence_match and run["tokens_match"]
            rows.append(row)
            result["reproduced"] = result["reproduced"] and row["reproduced"]
        result["engines"][engine_name] = {
            "kv_dtype": kv_dtype,
            "chaos": replay_cfg.get("chaos", ""),
            "weights_epoch": replay_cfg.get("weights_epoch", 0),
            "samples": rows,
            "reproduced": all(r["reproduced"] for r in rows),
        }
    return result


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("bundle", help="slo-capture-* dir or bundle.json path")
    ap.add_argument("--no-engine", action="store_true",
                    help="skip serving re-execution; re-score arms only")
    ap.add_argument("--max-samples", type=int, default=0,
                    help="replay at most N samples per engine (0 = all)")
    ap.add_argument("--init-seed", type=int, default=None,
                    help="llama.init weight seed (default: the bundle's "
                         "recorded sampler seed)")
    ap.add_argument("--json", action="store_true",
                    help="print the full result as JSON instead of a summary")
    args = ap.parse_args()

    result = replay(args.bundle, run_engine=not args.no_engine,
                    max_samples=args.max_samples, init_seed=args.init_seed)
    if args.json:
        print(json.dumps(result, indent=1, default=str))
    else:
        for name, entry in result["engines"].items():
            print(f"engine {name} (kv={entry['kv_dtype']}, "
                  f"chaos={entry['chaos'] or 'none'}):")
            for row in entry["samples"]:
                verdict = "REPRODUCED" if row["reproduced"] else "MISMATCH"
                rec = row["recorded"]
                print(f"  {row.get('request_id') or '<request>'}: {verdict} "
                      f"(top1_agree={rec.get('top1_agree')}, "
                      f"first_divergence={rec.get('first_divergence')}, "
                      f"tokens={row['tokens']})")
        print("reproduced" if result["reproduced"] else "MISMATCH: see above")
    return 0 if result["reproduced"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
