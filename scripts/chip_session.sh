#!/bin/bash
# Round-4 on-chip measurement session (VERDICT r3 #2/#3/#4/#6 + prefix bench).
# Each point runs in its OWN process: the KV-write lowering and kernel knobs
# are read at trace time and jit caches traces process-globally.
# Usage: bash scripts/chip_session.sh [outfile]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-/tmp/chip_session.jsonl}"
: > "$OUT"

run() {
  local tag="$1"; shift
  echo "=== $tag ($(date +%H:%M:%S)) ===" >&2
  local line
  line=$(env GOFR_BENCH_AUTO=0 "$@" timeout 1500 python bench.py 2>/dev/null | tail -1)
  echo "{\"tag\": \"$tag\", \"result\": ${line:-null}}" >> "$OUT"
  echo "$line" | head -c 400 >&2; echo >&2
}

# 0) step-time breakdown (writes to stderr only)
timeout 900 python scripts/profile_decode.py slot int8 2>&1 | grep -v WARNING >&2 || true

# 1) round-3 headline reproduction (regression check)
run r3_repro GOFR_BENCH_DEBUG=1

# 2) + int8 KV cache
run kv_int8 GOFR_BENCH_KV_QUANTIZE=int8 GOFR_BENCH_DEBUG=1

# 3) + pallas in-place append (vs select), bf16 KV and int8 KV
run pallas_append GOFR_KV_WRITE=pallas GOFR_BENCH_DEBUG=1
run pallas_append_kv8 GOFR_KV_WRITE=pallas GOFR_BENCH_KV_QUANTIZE=int8 GOFR_BENCH_DEBUG=1

# 4) long-context point (KV traffic dominates): 512-token prompts
run long_ctx GOFR_BENCH_PROMPT=512 GOFR_BENCH_REQUESTS=128
run long_ctx_kv8 GOFR_BENCH_PROMPT=512 GOFR_BENCH_REQUESTS=128 GOFR_BENCH_KV_QUANTIZE=int8
run long_ctx_kv8_pallas GOFR_BENCH_PROMPT=512 GOFR_BENCH_REQUESTS=128 \
    GOFR_BENCH_KV_QUANTIZE=int8 GOFR_KV_WRITE=pallas

# 5) sweep at the best-so-far variant (edit env per findings)
run sweep GOFR_BENCH_SWEEP=1 GOFR_BENCH_KV_QUANTIZE=int8

# 6) kernel A/B (attention kernels) at the new operating point
run pallas_ab GOFR_BENCH_PALLAS_AB=1 GOFR_BENCH_KV_QUANTIZE=int8

# 7) speculative decoding: latency mode single-stream gain. Round 5 made
# slot-layout spec PIPELINED (device-resident state); the sync point
# isolates what the pipelining contributes on top of drafting.
run spec_latency GOFR_BENCH_LATENCY=1 GOFR_BENCH_SPEC=4 GOFR_BENCH_REQUESTS=64
run spec_latency_sync GOFR_BENCH_LATENCY=1 GOFR_BENCH_SPEC=4 \
    GOFR_BENCH_PIPELINE=1 GOFR_BENCH_REQUESTS=64
run plain_latency GOFR_BENCH_LATENCY=1 GOFR_BENCH_REQUESTS=64
# spec under THROUGHPUT (full slots): weight-read amortization at occupancy
run spec_throughput GOFR_BENCH_SPEC=4

# 8) shared-prefix workload (paged + prefix cache A/B)
run prefix GOFR_BENCH_PREFIX=1 GOFR_BENCH_REQUESTS=128

# 8b) paged layout: headline + int8 + pallas in-place page append
run paged GOFR_BENCH_KV=paged
run paged_kv8 GOFR_BENCH_KV=paged GOFR_BENCH_KV_QUANTIZE=int8
run paged_kv8_pallas GOFR_BENCH_KV=paged GOFR_BENCH_KV_QUANTIZE=int8 \
    GOFR_PAGED_KV_WRITE=pallas
run paged_spec_latency GOFR_BENCH_KV=paged GOFR_BENCH_LATENCY=1 \
    GOFR_BENCH_SPEC=4 GOFR_BENCH_REQUESTS=64

# 9) the north-star model class: Llama-3-8B shape, int8 weights
run eight_b GOFR_BENCH_PRESET=eight_b GOFR_BENCH_REQUESTS=256 \
    GOFR_BENCH_SLOTS=64 GOFR_BENCH_PREFILL_BATCH=32
run eight_b_kv8 GOFR_BENCH_PRESET=eight_b GOFR_BENCH_REQUESTS=256 \
    GOFR_BENCH_SLOTS=64 GOFR_BENCH_PREFILL_BATCH=32 GOFR_BENCH_KV_QUANTIZE=int8

echo "session done -> $OUT" >&2
