#!/bin/bash
# Probe the TPU tunnel on a loop; on first success, run the full measurement
# session (scripts/chip_session.sh) and the decode profile. Designed to run in
# the background all round so no window of tunnel liveness is missed.
# Usage: bash scripts/chip_watch.sh [interval_seconds]
set -u
cd "$(dirname "$0")/.."
INTERVAL="${1:-300}"
LOG=/tmp/chip_watch.log
OUT=/tmp/chip_session.jsonl
: > "$LOG"

probe() {
  timeout 120 python - <<'EOF' >/dev/null 2>&1
import jax, numpy as np
x = jax.numpy.ones((256, 256), jax.numpy.bfloat16)
assert np.asarray(x @ x)[0, 0] == 256
assert jax.devices()[0].platform == "tpu"
EOF
}

while true; do
  if probe; then
    echo "$(date +%H:%M:%S) TPU alive — starting session" >> "$LOG"
    bash scripts/chip_session.sh "$OUT" >> "$LOG" 2>&1
    echo "$(date +%H:%M:%S) session finished" >> "$LOG"
    exit 0
  fi
  echo "$(date +%H:%M:%S) probe failed; sleeping ${INTERVAL}s" >> "$LOG"
  sleep "$INTERVAL"
done
