"""Decode step-time breakdown on the attached chip (VERDICT r3 weak #2).

Times the engine-shaped decode chunk (scan of decode_step + argmax) at a
grid of (slots, Smax, K) and prints per-step device time + implied
bandwidth. The Smax slope isolates KV-cache traffic (attention read +
masked-select append rewrite); the intercept is weights + fixed overhead.

Usage: python scripts/profile_decode.py [slot|paged] [int8|bf16]
Env: N=slots K=chunk SMAXES=256,512,1024 ITERS=8
"""

from __future__ import annotations

import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from gofr_tpu.models import LlamaConfig, llama


def main() -> None:
    layout = sys.argv[1] if len(sys.argv) > 1 else "slot"
    quant = sys.argv[2] if len(sys.argv) > 2 else "int8"
    slots = int(os.environ.get("N", "128"))
    K = int(os.environ.get("K", "32"))
    smaxes = [int(s) for s in os.environ.get("SMAXES", "256,512,1024").split(",")]
    iters = int(os.environ.get("ITERS", "8"))

    cfg = LlamaConfig.one_b()
    params = llama.init(cfg, jax.random.key(0))
    if quant == "int8":
        from gofr_tpu.ops.quant import quantize_tree

        params = jax.jit(quantize_tree)(params)
    from gofr_tpu.ops.quant import quantized_bytes

    wbytes = float(quantized_bytes(params))
    dev = jax.devices()[0]
    print(f"device={dev.device_kind} layout={layout} quant={quant} "
          f"slots={slots} K={K} weight_GB={wbytes/1e9:.3f}", flush=True)

    kvb = 2 * cfg.num_layers * cfg.num_kv_heads * cfg.head_size * jnp.dtype(cfg.dtype).itemsize

    for smax in smaxes:
        if layout == "paged":
            page = 128
            pages_per_slot = smax // page
            total_pages = slots * pages_per_slot
            cache = llama.make_paged_cache(cfg, total_pages, page)
            table = jnp.asarray(
                np.arange(total_pages, dtype=np.int32).reshape(slots, pages_per_slot))

            @partial(jax.jit, static_argnums=(2,), donate_argnums=(1,))
            def chunk(params, cache, steps, toks, pos, table):
                def body(carry, _):
                    t, p, c = carry
                    logits, c = llama.decode_step_paged(cfg, params, t, p, c, table)
                    nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                    return (nxt, p + 1, c), nxt

                (t, p, c), out = jax.lax.scan(body, (toks, pos, cache), None, length=steps)
                return out.T, c

            args = (table,)
        else:
            cache = llama.make_cache(cfg, slots, smax)

            @partial(jax.jit, static_argnums=(2,), donate_argnums=(1,))
            def chunk(params, cache, steps, toks, pos):
                def body(carry, _):
                    t, p, c = carry
                    logits, c = llama.decode_step(cfg, params, t, p, c)
                    nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                    return (nxt, p + 1, c), nxt

                (t, p, c), out = jax.lax.scan(body, (toks, pos, cache), None, length=steps)
                return out.T, c

            args = ()

        toks = jnp.zeros((slots,), jnp.int32)
        pos = jnp.asarray(np.full(slots, smax // 2, np.int32))

        def timed(k_steps: int, cache):
            """Seconds per call at chunk length k_steps, RTT included —
            np.asarray forces a real readback (block_until_ready on the
            tunneled backend returns before the remote chain drains)."""
            out, cache = chunk(params, cache, k_steps, toks, pos, *args)
            np.asarray(out)  # compile + settle
            t0 = time.monotonic()
            for _ in range(iters):
                out, cache = chunk(params, cache, k_steps, toks, pos, *args)
                np.asarray(out)
            return (time.monotonic() - t0) / iters, cache

        k_lo = max(1, K // 4)
        t_lo, cache = timed(k_lo, cache)
        t_hi, cache = timed(K, cache)
        # differencing cancels fixed per-call cost (dispatch + tunnel RTT)
        dt = (t_hi - t_lo) / (K - k_lo)
        cache_gb = slots * smax * kvb / 1e9
        print(f"  Smax={smax:5d} cache_GB={cache_gb:6.3f}  {dt*1e3:7.3f} ms/step "
              f"(call: K={k_lo} {t_lo*1e3:.1f}ms, K={K} {t_hi*1e3:.1f}ms)  "
              f"{slots/dt:8.0f} tok/s  weights-only-bound={wbytes/819e9*1e3:.2f} ms",
              flush=True)
        del cache


if __name__ == "__main__":
    main()
